"""Post-training quantization: trained checkpoint -> calibrated int8
serving artifact.

The supported route from training output to quantized production
serving (ROADMAP item 5):

1. :func:`quantize_checkpoint` loads the newest VALID checkpoint under
   a prefix (``checkpoint.load_latest_valid`` semantics: manifest CRCs
   verified, torn checkpoints skipped), runs calibration batches
   through the fp32 graph (quantize/calibrate.py observers), and
   rewrites every FullyConnected / Convolution into the per-channel
   int8 serving ops (``_contrib_quantized_fc_int8`` /
   ``_contrib_quantized_conv_int8``, ops/quantization_ops.py — int8
   MXU dots with the rescale fused into the epilogue via the Pallas
   kernel).
2. The result is a :class:`QuantizedParams` ARTIFACT on disk — symbol
   json + params (int8 weights, fp32 per-channel scales, untouched
   fp32 bias/aux) + a CRC'd manifest carrying the calibration
   metadata — written through ``checkpoint.atomic_writer`` so a crash
   mid-write never tears it, and loaded back through the same
   checksum-verified fallback walk as training checkpoints.
3. ``serve.ModelRegistry.swap(quantized=artifact)`` hot-swaps it under
   live traffic (drain semantics unchanged), optionally after a
   shadow A/B canary (``enable_shadow``). See docs/quantization.md.

Per-channel weight / per-tensor activation granularity follows
TPU-MLIR's calibration design: weight channels get exact fp32 scales
(free at serving time — they fold into the dot epilogue), activations
share one calibrated scale per tensor (a per-element scale would break
the single-dot structure the MXU wants).
"""
from __future__ import annotations

import json as _json
import os

import numpy as _np

from .. import telemetry as _tm
from ..base import MXNetError
from .calibrate import collect_activation_ranges

__all__ = ["quantize_checkpoint", "quantize_symbol", "QuantizedParams",
           "validate_excluded_names"]

_QUANT_OPS = {"FullyConnected": "_contrib_quantized_fc_int8",
              "Convolution": "_contrib_quantized_conv_int8"}
_INT8_MAX = 127.0


def validate_excluded_names(symbol, excluded_sym_names):
    """``excluded_sym_names`` entries must name actual op nodes of the
    graph — a typo'd exclusion silently quantizing the layer it meant
    to protect is exactly the bug this guards. Raises
    :class:`MXNetError` naming every stranger; returns the set."""
    from ..symbol.symbol import _topo
    excluded = set(excluded_sym_names or ())
    node_names = {n.name for n in _topo(symbol._entries) if not n.is_var}
    strangers = sorted(excluded - node_names)
    if strangers:
        raise MXNetError(
            "excluded_sym_names %s name no op node in the graph "
            "(graph has: %s)" % (strangers, sorted(node_names)))
    return excluded


def _per_channel_quantize(w):
    """fp32 weight -> (int8 weight, fp32 per-channel scales) with
    channel = axis 0 (FC: num_hidden; Conv: num_filter). Zero-range
    channels get scale 1.0 and quantize to zeros (no NaN/inf)."""
    w = _np.asarray(w, dtype=_np.float32)
    amax = _np.max(_np.abs(w.reshape(w.shape[0], -1)), axis=1)
    scale = _np.where(amax > 0, amax / _INT8_MAX, 1.0).astype(_np.float32)
    q = _np.clip(_np.round(w / scale.reshape((-1,) + (1,) * (w.ndim - 1))),
                 -_INT8_MAX, _INT8_MAX).astype(_np.int8)
    return q, scale


def _act_scale(ranges):
    """Calibrated (min, max) -> static activation scale 127/amax
    (1.0 for a degenerate range: a constant-zero activation tensor
    quantizes to zeros, never NaN)."""
    amax = max(abs(float(ranges[0])), abs(float(ranges[1])))
    return _INT8_MAX / amax if amax > 0 else 1.0


def quantize_symbol(symbol, arg_params, act_ranges, excluded_sym_names=(),
                    logger=None):
    """Rewrite FullyConnected / Convolution nodes into the per-channel
    int8 serving ops; returns ``(qsym, qarg_params, meta)``.

    A node quantizes when it is not excluded, its weight is a graph
    parameter present in ``arg_params``, and ``act_ranges`` carries a
    calibrated range for its data input (nodes failing any of these
    stay fp32 — logged, never silently mis-scaled). ``qarg_params``
    drops each quantized node's fp32 weight and adds
    ``<node>_weight_q`` (int8) + ``<node>_w_scale`` (fp32 per-channel);
    bias and every other parameter pass through untouched.
    """
    import logging
    from ..ndarray.ndarray import array as nd_array
    from ..ops import registry as _reg
    from ..symbol import symbol as _S
    log = logger or logging
    excluded = validate_excluded_names(symbol, excluded_sym_names)
    arg_params = dict(arg_params or {})
    qparams = dict(arg_params)
    meta = {}

    new_of = {}        # id(old_node) -> Symbol (all outputs)

    def _sub(node, oi):
        return new_of[id(node)][oi]

    for node in _S._topo(symbol._entries):
        if node.is_var:
            if node.name in arg_params:
                # bake the known param shape into the rebuilt variable
                # so shape inference works on the quantized graph
                attrs = dict(node.attrs or {})
                attrs["__shape__"] = tuple(arg_params[node.name].shape)
                nv = _S._Node(None, node.name, attrs, is_aux=node.is_aux)
                new_of[id(node)] = _S.Symbol([(nv, 0)])
            else:
                new_of[id(node)] = _S.Symbol([(node, 0)])
            continue
        inputs_kw = {}
        for in_name, (src, oi) in zip(node.in_names or [], node.inputs):
            inputs_kw[in_name] = _sub(src, oi)
        attrs = dict(node.attrs or {})
        quantize = node.op in _QUANT_OPS and node.name not in excluded
        wsrc = None
        if quantize:
            slot = (node.in_names or [])
            if "weight" not in slot or "data" not in slot:
                quantize = False
            else:
                wsrc = node.inputs[slot.index("weight")][0]
                if not wsrc.is_var or wsrc.name not in arg_params:
                    quantize = False     # computed weight: stays fp32
        if quantize:
            dsrc, doi = node.inputs[(node.in_names or []).index("data")]
            rng = act_ranges.get((dsrc.name, doi))
            if rng is None:
                log.warning("no calibrated range for %r input of %r; "
                            "layer stays fp32", dsrc.name, node.name)
                quantize = False
            elif not (_np.isfinite(rng[0]) and _np.isfinite(rng[1])):
                log.warning("non-finite calibrated range %s for %r; "
                            "layer stays fp32", rng, node.name)
                quantize = False
        if not quantize:
            out = _S._apply_op(_reg.get_op(node.op), [],
                               {**attrs, **inputs_kw}, node.name)
            new_of[id(node)] = out
            continue

        wq, wscale = _per_channel_quantize(
            arg_params[wsrc.name].asnumpy()
            if hasattr(arg_params[wsrc.name], "asnumpy")
            else arg_params[wsrc.name])
        act = _act_scale(rng)
        qparams.pop(wsrc.name, None)
        qparams[node.name + "_weight_q"] = nd_array(wq, dtype=_np.int8)
        qparams[node.name + "_w_scale"] = nd_array(wscale)
        wq_sym = _S.Variable(node.name + "_weight_q", shape=wq.shape,
                             dtype="int8")
        ws_sym = _S.Variable(node.name + "_w_scale", shape=wscale.shape)

        if node.op == "FullyConnected":
            keep = ("num_hidden", "no_bias", "flatten")
        else:
            keep = ("kernel", "stride", "dilate", "pad", "num_filter",
                    "num_group", "no_bias", "layout")
        qattrs = {k: attrs[k] for k in keep if k in attrs}
        qattrs["act_scale"] = act
        args = [inputs_kw["data"], wq_sym, ws_sym]
        bias_sym = inputs_kw.get("bias")
        if bias_sym is not None and not attrs.get("no_bias", False):
            args.append(bias_sym)
        else:
            qattrs["no_bias"] = True
        qnode = _S._apply_op(_reg.get_op(_QUANT_OPS[node.op]), args,
                             qattrs, node.name + "_int8")
        meta[node.name] = {"op": node.op, "act_scale": act,
                           "channels": int(wq.shape[0]),
                           "act_range": [float(rng[0]), float(rng[1])]}
        new_of[id(node)] = qnode

    entries = []
    for (node, oi) in symbol._entries:
        entries.extend(new_of[id(node)][oi]._entries)
    return _S.Symbol(entries), qparams, meta


class QuantizedParams(object):
    """A calibrated int8 serving artifact: quantized symbol + params
    (per-channel int8 weights, fp32 scales, fp32 bias/aux) + manifest
    metadata. Produced by :func:`quantize_checkpoint`, consumed by
    ``serve.ModelRegistry.swap(quantized=...)`` / ``enable_shadow``.
    """

    def __init__(self, symbol, arg_params, aux_params, meta, prefix=None):
        self.symbol = symbol
        self.arg_params = dict(arg_params)
        self.aux_params = dict(aux_params or {})
        self.meta = dict(meta or {})
        self.prefix = prefix

    @property
    def symbol_json(self):
        return self.symbol.tojson()

    def _save_dict(self):
        """Checkpoint-format key mapping (``arg:``/``aux:`` prefixed) —
        the ONE place the artifact's on-disk and in-memory blob key
        scheme is defined."""
        save_dict = {("arg:%s" % k): v for k, v in self.arg_params.items()}
        save_dict.update({("aux:%s" % k): v
                          for k, v in self.aux_params.items()})
        return save_dict

    def param_bytes(self):
        """The params blob in the ``mx.nd.save`` checkpoint format —
        exactly what ``serving.Predictor`` / ``serve.ModelRegistry``
        consume."""
        import tempfile
        from ..ndarray import utils as _utils
        fd, tmp = tempfile.mkstemp(suffix=".params")
        os.close(fd)
        try:
            _utils.save(tmp, self._save_dict())
            with open(tmp, "rb") as f:
                return f.read()
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def save(self, prefix):
        """Write the artifact under ``prefix`` (symbol json + params +
        CRC'd manifest, every file through the atomic write path) and
        return ``prefix``. Restorable by :meth:`load` with
        ``load_latest_valid``-grade validation."""
        from ..checkpoint import write_manifest
        from ..ndarray import utils as _utils
        sym_file = "%s-symbol.json" % prefix
        self.symbol.save(sym_file)               # atomic_writer inside
        param_file = "%s-%04d.params" % (prefix, 0)
        _utils.save(param_file, self._save_dict())  # atomic_writer inside
        write_manifest(prefix, 0,
                       {"params": param_file, "symbol": sym_file},
                       extra={"quantized": {"format": 1,
                                            "layers": self.meta}})
        self.prefix = prefix
        if _tm._enabled:
            _tm.counter("quantize/checkpoints_total",
                        "Quantized int8 artifacts written").inc()
        return prefix

    @classmethod
    def load(cls, prefix):
        """Load the newest VALID artifact under ``prefix``: manifest
        CRCs verified, torn artifacts skipped (the
        ``checkpoint.load_latest_valid`` walk). Raises
        :class:`MXNetError` when nothing loads or the checkpoint is
        not a quantized artifact."""
        from ..checkpoint import load_latest_valid, manifest_path
        state = load_latest_valid(prefix)
        if state is None:
            raise MXNetError("no quantized artifact under %r" % prefix)
        if state.symbol is None:
            raise MXNetError("artifact %r has no symbol file" % prefix)
        try:
            with open(manifest_path(prefix, state.epoch)) as f:
                man = _json.load(f)
        except (OSError, ValueError) as e:
            raise MXNetError("artifact manifest unreadable: %s" % e) from e
        qmeta = man.get("quantized")
        if qmeta is None:
            raise MXNetError(
                "%r is a plain checkpoint, not a quantized artifact "
                "(run quantize_checkpoint to produce one)" % prefix)
        return cls(state.symbol, state.arg_params, state.aux_params,
                   qmeta.get("layers", {}), prefix=prefix)


def quantize_checkpoint(prefix, calib_data, epoch=None, out_prefix=None,
                        calib_mode="minmax", excluded_sym_names=(),
                        data_names=("data",), num_calib_examples=None,
                        symbol=None, logger=None):
    """Trained checkpoint -> calibrated int8 artifact on disk.

    Parameters
    ----------
    prefix : checkpoint prefix (``model.save_checkpoint`` layout). With
        ``epoch=None`` the newest checkpoint whose manifest checksums
        verify is used (torn ones skipped); an explicit ``epoch`` pins
        one.
    calib_data : batch iterable fed through the fp32 graph to calibrate
        activation ranges (quantize/calibrate.py).
    calib_mode : ``"minmax"``/``"naive"`` (exact ranges) or
        ``"percentile"``/``"entropy"`` (outlier-clipped at
        ``MXNET_QUANT_PERCENTILE``), or an observer factory.
    excluded_sym_names : op-node names kept fp32; every entry must name
        a real node (:func:`validate_excluded_names`).
    out_prefix : artifact location; default ``<prefix>-int8``.
    symbol : override the checkpointed symbol (symbol-less prefixes).

    Returns the saved :class:`QuantizedParams` (``.prefix`` names the
    artifact on disk; reload anytime with ``QuantizedParams.load``).
    """
    from ..checkpoint import load_latest_valid
    from ..model import load_checkpoint as _load_ckpt
    if epoch is not None:
        sym, arg_params, aux_params = _load_ckpt(prefix, epoch)
    else:
        state = load_latest_valid(prefix)
        if state is None:
            raise MXNetError("no checkpoint under %r to quantize" % prefix)
        sym, arg_params, aux_params = (state.symbol, state.arg_params,
                                       state.aux_params)
    if symbol is not None:
        sym = symbol
    if sym is None:
        raise MXNetError(
            "checkpoint %r has no symbol file; pass symbol=" % prefix)
    stats = collect_activation_ranges(
        sym, arg_params, aux_params, calib_data, data_names=data_names,
        observer=calib_mode, num_calib_examples=num_calib_examples)
    qsym, qarg, meta = quantize_symbol(sym, arg_params, stats,
                                       excluded_sym_names, logger=logger)
    if not meta:
        raise MXNetError(
            "nothing quantized under %r: no FullyConnected/Convolution "
            "node has a parameter weight and a calibrated input range"
            % prefix)
    qp = QuantizedParams(qsym, qarg, aux_params, meta)
    qp.save(out_prefix or (prefix + "-int8"))
    return qp
