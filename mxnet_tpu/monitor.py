"""Monitor: spy on tensor statistics during execution.

Reference: python/mxnet/monitor.py (Monitor installed via executor
monitor callback, GraphExecutor::ExecuteMonCallback
src/executor/graph_executor.cc:1295). Here the hook rides the Block
forward hooks / Executor output capture.
"""
from __future__ import annotations

import re

from .ndarray.ndarray import NDArray

__all__ = ["Monitor"]


class Monitor(object):
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def stat_func(x):
                return x.norm() / (x.size ** 0.5)
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

    def install(self, exe):
        """Attach to an Executor (reference: monitor.py install) — wires
        the per-op monitor callback so intermediate outputs are spied,
        like GraphExecutor::ExecuteMonCallback."""
        def callback(name, arr):
            if self.activated and self.re_prog.match(name):
                self.queue.append((self.step, name, self.stat_func(arr)))
        exe.set_monitor_callback(callback)
        self.exes.append(exe)

    def install_block(self, block):
        """Attach to a Gluon block tree via forward hooks."""
        def hook(blk, _in, out):
            if not self.activated:
                return
            outs = out if isinstance(out, (list, tuple)) else [out]
            for i, o in enumerate(outs):
                if isinstance(o, NDArray) and \
                        self.re_prog.match(blk.name):
                    self.queue.append((self.step, "%s_output%d"
                                       % (blk.name, i),
                                       self.stat_func(o)))
        block.apply(lambda b: b.register_forward_hook(hook))

    def tic(self):
        """Start collecting for this step (reference: monitor.py tic)."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        """Collect stats (reference: monitor.py toc)."""
        if not self.activated:
            return []
        self.activated = False
        for exe in self.exes:
            for name, arr in getattr(exe, "output_dict", {}).items():
                if self.re_prog.match(name):
                    self.queue.append((self.step, name,
                                       self.stat_func(arr)))
        res = []
        queue = self.queue
        if self.sort:
            queue = sorted(queue, key=lambda x: x[1])
        for n, k, v_list in queue:
            if isinstance(v_list, NDArray):
                v_list = [v_list]
            assert isinstance(v_list, list)
            s = ",".join("%f" % float(v.asnumpy().reshape(-1)[0])
                         if isinstance(v, NDArray) else str(v)
                         for v in v_list)
            res.append((n, k, s))
        self.queue = []
        return res

    def toc_print(self):
        res = self.toc()
        for n, k, v in res:
            print("Batch: %7d %30s %s" % (n, k, v))
