#!/usr/bin/env python
"""Collective-bandwidth microbenchmark over a device mesh.

Reference analog: tools/bandwidth/measure.py (KVStore push/pull
bandwidth across GPUs/machines). On TPU the communication substrate is
XLA collectives over ICI, so this measures what actually bounds
data-parallel training: psum (allreduce) / all_gather / ppermute
bandwidth per device as a function of payload size.

Usage (virtual CPU mesh for a smoke run):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/bandwidth.py --sizes 1,8,64 --collective psum
"""
import argparse
import functools
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--sizes", default="1,4,16,64",
                    help="payload sizes in MB, comma separated")
    ap.add_argument("--collective", default="psum",
                    choices=["psum", "all_gather", "ppermute"])
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--axis", default="x")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from jax import shard_map

    devs = jax.devices()
    n = len(devs)
    mesh = Mesh(np.array(devs), (args.axis,))
    print("devices: %d x %s" % (n, devs[0].device_kind))

    def body(x):
        if args.collective == "psum":
            return jax.lax.psum(x, args.axis)
        if args.collective == "all_gather":
            return jax.lax.all_gather(x, args.axis)
        perm = [(i, (i + 1) % n) for i in range(n)]
        return jax.lax.ppermute(x, args.axis, perm)

    for mb in [float(s) for s in args.sizes.split(",")]:
        elems = int(mb * 1e6 / 4)
        per_dev = max(1, elems // n)
        fn = jax.jit(shard_map(body, mesh=mesh, in_specs=P(args.axis),
                               out_specs=P() if args.collective !=
                               "ppermute" else P(args.axis),
                               check_vma=False))
        x = jnp.ones((per_dev * n,), jnp.float32)
        fn(x).block_until_ready()            # compile
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = fn(x)
        out.block_until_ready()
        dt = (time.perf_counter() - t0) / args.iters
        payload = per_dev * 4
        # allreduce moves ~2*(n-1)/n of the payload per device
        algo_bytes = payload * (2 * (n - 1) / n
                                if args.collective == "psum" else
                                (n - 1) / n if args.collective ==
                                "all_gather" else 1.0)
        print("%-12s %8.2f MB/dev  %8.3f ms  %8.2f GB/s/dev (algo)"
              % (args.collective, payload / 1e6, dt * 1e3,
                 algo_bytes / dt / 1e9))


if __name__ == "__main__":
    main()
