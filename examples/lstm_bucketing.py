"""Bucketed LSTM language model via the legacy mx.rnn API — the
capability analog of the reference's example/rnn/lstm_bucketing.py
(PTB LSTM with BucketSentenceIter + BucketingModule).

With --data pointing at a tokenized text file (one sentence per line,
space-separated tokens) it trains on that corpus; without it, a
synthetic modular-arithmetic corpus is generated so the example runs
self-contained.

    python examples/lstm_bucketing.py --num-epochs 5 --num-hidden 64
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import mxnet_tpu as mx  # noqa: E402


def build_vocab(lines):
    vocab = {}
    for line in lines:
        for tok in line.split():
            if tok not in vocab:
                vocab[tok] = len(vocab) + 1       # 0 = padding
    return vocab


def encode(lines, vocab):
    return [[vocab[t] for t in line.split()] for line in lines]


def synthetic_corpus(n=400, vocab_size=30, seed=0):
    rng = np.random.RandomState(seed)
    sents = []
    for _ in range(n):
        start = rng.randint(0, vocab_size)
        ln = rng.randint(4, 17)
        sents.append([(start + k) % vocab_size + 1 for k in range(ln)])
    return sents, vocab_size + 1


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data", type=str, default=None,
                    help="tokenized text file; synthetic corpus if unset")
    ap.add_argument("--num-hidden", type=int, default=64)
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--num-embed", type=int, default=64)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--buckets", type=str, default="8,16,24,32")
    ap.add_argument("--num-epochs", type=int, default=5)
    ap.add_argument("--lr", type=float, default=0.5)
    ap.add_argument("--disp-batches", type=int, default=50)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    if args.data:
        lines = [l.strip() for l in open(args.data) if l.strip()]
        vocab = build_vocab(lines)
        sentences = encode(lines, vocab)
        vocab_size = len(vocab) + 1
    else:
        sentences, vocab_size = synthetic_corpus()

    buckets = [int(b) for b in args.buckets.split(",")]
    it = mx.rnn.BucketSentenceIter(sentences, args.batch_size,
                                   buckets=buckets, invalid_label=-1)

    stack = mx.rnn.SequentialRNNCell()
    for i in range(args.num_layers):
        stack.add(mx.rnn.LSTMCell(num_hidden=args.num_hidden,
                                  prefix="lstm_l%d_" % i))

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data, input_dim=vocab_size,
                                 output_dim=args.num_embed, name="embed")
        stack.reset()
        outputs, _ = stack.unroll(seq_len, embed, merge_outputs=True,
                                  batch_size=args.batch_size)
        pred = mx.sym.Reshape(outputs, shape=(-1, args.num_hidden))
        pred = mx.sym.FullyConnected(pred, num_hidden=vocab_size,
                                     name="pred")
        label_f = mx.sym.Reshape(label, shape=(-1,))
        net = mx.sym.SoftmaxOutput(pred, label_f, name="softmax",
                                   use_ignore=True, ignore_label=-1)
        return net, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen,
                                 default_bucket_key=it.default_bucket_key,
                                 context=mx.cpu())
    mod.fit(it,
            eval_metric=mx.metric.Perplexity(ignore_label=-1),
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            initializer=mx.init.Xavier(factor_type="in", magnitude=2.34),
            num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(
                args.batch_size, args.disp_batches))


if __name__ == "__main__":
    main()
