"""Detection stack: MultiBoxTarget/MultiBoxDetection/Proposal ops,
ImageDetIter + bbox augmenters, SSD smoke training.

Reference behavior: src/operator/contrib/multibox_target.cc,
multibox_detection.cc, proposal.cc, src/io/image_det_aug_default.cc,
python/mxnet/image/detection.py.
"""
import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import ndarray as nd

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


# ---------------------------------------------------------------------------
# MultiBoxTarget


def _mbt(anchors, labels, cls_pred, **kw):
    return nd.contrib.MultiBoxTarget(
        mx.nd.array(anchors), mx.nd.array(labels), mx.nd.array(cls_pred),
        **kw)


def test_multibox_target_perfect_match():
    # one anchor exactly over the gt box -> positive with zero offsets
    anchors = np.array([[[0.1, 0.1, 0.5, 0.5],
                         [0.6, 0.6, 0.9, 0.9]]], np.float32)
    labels = np.array([[[1.0, 0.1, 0.1, 0.5, 0.5]]], np.float32)
    cls_pred = np.zeros((1, 3, 2), np.float32)
    loc_t, loc_m, cls_t = _mbt(anchors, labels, cls_pred)
    assert loc_t.shape == (1, 8) and cls_t.shape == (1, 2)
    ct = cls_t.asnumpy()[0]
    assert ct[0] == 2.0          # gt class 1 -> target 1+1
    assert ct[1] == 0.0          # background
    lm = loc_m.asnumpy()[0]
    np.testing.assert_array_equal(lm, [1, 1, 1, 1, 0, 0, 0, 0])
    np.testing.assert_allclose(loc_t.asnumpy()[0][:4], 0.0, atol=1e-5)


def test_multibox_target_encoding_roundtrip():
    # encode then decode via MultiBoxDetection must recover the gt box
    anchors = np.array([[[0.2, 0.2, 0.6, 0.7]]], np.float32)
    gt = np.array([0.25, 0.15, 0.55, 0.66], np.float32)
    labels = np.concatenate([[3.0], gt]).reshape(1, 1, 5).astype(np.float32)
    cls_pred = np.zeros((1, 5, 1), np.float32)
    loc_t, loc_m, cls_t = _mbt(anchors, labels, cls_pred,
                               overlap_threshold=0.3)
    assert cls_t.asnumpy()[0, 0] == 4.0
    # decode: variances match defaults
    v = (0.1, 0.1, 0.2, 0.2)
    a = anchors[0, 0]
    aw, ah = a[2] - a[0], a[3] - a[1]
    ax, ay = (a[0] + a[2]) / 2, (a[1] + a[3]) / 2
    t = loc_t.asnumpy()[0]
    ox = t[0] * v[0] * aw + ax
    oy = t[1] * v[1] * ah + ay
    ow = np.exp(t[2] * v[2]) * aw / 2
    oh = np.exp(t[3] * v[3]) * ah / 2
    np.testing.assert_allclose(
        [ox - ow, oy - oh, ox + ow, oy + oh], gt, rtol=1e-4, atol=1e-5)


def test_multibox_target_bipartite_claims_best():
    # two anchors both overlap the single gt; only the better one is
    # positive via bipartite matching (threshold disabled by 0.9)
    anchors = np.array([[[0.0, 0.0, 0.5, 0.5],
                         [0.05, 0.05, 0.55, 0.55]]], np.float32)
    labels = np.array([[[0.0, 0.05, 0.05, 0.55, 0.55]]], np.float32)
    cls_pred = np.zeros((1, 2, 2), np.float32)
    _, loc_m, cls_t = _mbt(anchors, labels, cls_pred,
                           overlap_threshold=0.95)
    ct = cls_t.asnumpy()[0]
    assert ct[1] == 1.0 and ct[0] == 0.0
    np.testing.assert_array_equal(loc_m.asnumpy()[0], [0] * 4 + [1] * 4)


def test_multibox_target_negative_mining():
    # 4 anchors, 1 positive; ratio 1 -> exactly 1 negative kept, the
    # other two anchors ignored (-1)
    anchors = np.zeros((1, 4, 4), np.float32)
    anchors[0, 0] = [0.1, 0.1, 0.4, 0.4]
    anchors[0, 1] = [0.5, 0.5, 0.6, 0.6]
    anchors[0, 2] = [0.7, 0.7, 0.8, 0.8]
    anchors[0, 3] = [0.85, 0.85, 0.95, 0.95]
    labels = np.array([[[2.0, 0.1, 0.1, 0.4, 0.4]]], np.float32)
    cls_pred = np.zeros((1, 3, 4), np.float32)
    # anchor 2 least background-like -> hardest negative
    cls_pred[0, 0] = [5.0, 5.0, -5.0, 5.0]
    loc_t, loc_m, cls_t = _mbt(anchors, labels, cls_pred,
                               negative_mining_ratio=1.0,
                               negative_mining_thresh=0.5)
    ct = cls_t.asnumpy()[0]
    assert ct[0] == 3.0                     # positive, class 2 + 1
    assert ct[2] == 0.0                     # mined negative
    assert ct[1] == -1.0 and ct[3] == -1.0  # ignored


# ---------------------------------------------------------------------------
# MultiBoxDetection


def test_multibox_detection_decode_and_nms():
    anchors = np.array([[[0.1, 0.1, 0.5, 0.5],
                         [0.12, 0.12, 0.52, 0.52],
                         [0.6, 0.6, 0.9, 0.9]]], np.float32)
    # zero offsets -> boxes == anchors
    loc_pred = np.zeros((1, 12), np.float32)
    cls_prob = np.array([[[0.1, 0.2, 0.8],     # background
                          [0.8, 0.1, 0.1],     # class 0
                          [0.1, 0.7, 0.1]]], np.float32)  # class 1
    out = nd.contrib.MultiBoxDetection(
        mx.nd.array(cls_prob), mx.nd.array(loc_pred), mx.nd.array(anchors),
        nms_threshold=0.5, threshold=0.05, force_suppress=True)
    o = out.asnumpy()[0]
    assert out.shape == (1, 3, 6)
    # of the two overlapping anchors force_suppress keeps the higher;
    # the far-away third anchor survives regardless of class
    kept = o[o[:, 0] >= 0]
    assert len(kept) == 2
    assert kept[0][0] == 0.0 and abs(kept[0][1] - 0.8) < 1e-5
    np.testing.assert_allclose(kept[0][2:], anchors[0, 0], atol=1e-5)
    np.testing.assert_allclose(kept[1][2:], anchors[0, 2], atol=1e-5)


def test_multibox_detection_per_class_nms():
    # same boxes, different classes: per-class NMS keeps both
    anchors = np.array([[[0.1, 0.1, 0.5, 0.5],
                         [0.12, 0.12, 0.52, 0.52]]], np.float32)
    loc_pred = np.zeros((1, 8), np.float32)
    cls_prob = np.array([[[0.1, 0.2],
                          [0.8, 0.1],
                          [0.1, 0.7]]], np.float32)
    out = nd.contrib.MultiBoxDetection(
        mx.nd.array(cls_prob), mx.nd.array(loc_pred), mx.nd.array(anchors),
        nms_threshold=0.5, threshold=0.05)
    o = out.asnumpy()[0]
    kept = o[o[:, 0] >= 0]
    assert len(kept) == 2
    assert set(kept[:, 0]) == {0.0, 1.0}


# ---------------------------------------------------------------------------
# Proposal


def test_proposal_shapes_and_clip():
    rng = np.random.RandomState(0)
    B, A, H, W = 1, 3, 4, 5
    cls_prob = rng.uniform(0, 1, (B, 2 * A, H, W)).astype(np.float32)
    bbox_pred = (rng.randn(B, 4 * A, H, W) * 0.1).astype(np.float32)
    im_info = np.array([[64.0, 80.0, 1.0]], np.float32)
    rois = nd.contrib.Proposal(
        mx.nd.array(cls_prob), mx.nd.array(bbox_pred), mx.nd.array(im_info),
        rpn_pre_nms_top_n=50, rpn_post_nms_top_n=8, feature_stride=16,
        scales=(8,), ratios=(0.5, 1, 2), rpn_min_size=4)
    r = rois.asnumpy()
    assert r.shape == (8, 5)
    assert (r[:, 0] == 0).all()
    assert (r[:, 1] >= 0).all() and (r[:, 3] <= 79).all()
    assert (r[:, 2] >= 0).all() and (r[:, 4] <= 63).all()
    # well-formed boxes
    assert (r[:, 3] >= r[:, 1]).all() and (r[:, 4] >= r[:, 2]).all()


def test_proposal_output_score_and_order():
    rng = np.random.RandomState(1)
    B, A, H, W = 1, 1, 3, 3
    cls_prob = rng.uniform(0, 1, (B, 2 * A, H, W)).astype(np.float32)
    bbox_pred = np.zeros((B, 4 * A, H, W), np.float32)
    im_info = np.array([[48.0, 48.0, 1.0]], np.float32)
    rois, scores = nd.contrib.Proposal(
        mx.nd.array(cls_prob), mx.nd.array(bbox_pred), mx.nd.array(im_info),
        rpn_pre_nms_top_n=9, rpn_post_nms_top_n=4, feature_stride=16,
        scales=(4,), ratios=(1,), rpn_min_size=2, output_score=True,
        threshold=0.99)
    s = scores.asnumpy().ravel()
    # scores non-increasing (sorted by objectness)
    assert (np.diff(s) <= 1e-6).all()
    assert rois.shape == (4, 5) and scores.shape == (4, 1)


# ---------------------------------------------------------------------------
# ImageDetIter + detection augmenters


def _make_det_dataset(tmp_path, n=6, size=48):
    import cv2
    paths = []
    rng = np.random.RandomState(0)
    for i in range(n):
        img = (rng.rand(size, size, 3) * 255).astype(np.uint8)
        p = os.path.join(str(tmp_path), "im%d.png" % i)
        cv2.imwrite(p, img)
        # two boxes, flat [cls, x1, y1, x2, y2] * 2
        lbl = [0, 0.1, 0.1, 0.4, 0.5, 1, 0.5, 0.4, 0.9, 0.8]
        paths.append((lbl, "im%d.png" % i))
    return paths


def test_image_det_iter_shapes_and_labels(tmp_path):
    import mxnet_tpu.image as img
    data = _make_det_dataset(tmp_path)
    it = img.ImageDetIter(batch_size=4, data_shape=(3, 32, 32),
                          imglist=data, path_root=str(tmp_path),
                          aug_list=[img.DetForceResizeAug((32, 32))])
    b = next(it)
    assert b.data[0].shape == (4, 3, 32, 32)
    assert b.label[0].shape == (4, 2, 5)
    lab = b.label[0].asnumpy()
    np.testing.assert_allclose(lab[0, 0], [0, 0.1, 0.1, 0.4, 0.5],
                               rtol=1e-5)


def test_det_horizontal_flip_updates_boxes(tmp_path):
    import mxnet_tpu.image as img
    arr = mx.nd.array(np.zeros((10, 10, 3), np.uint8))
    lbl = np.array([[0, 0.1, 0.2, 0.4, 0.6], [-1] * 5], np.float32)
    aug = img.DetHorizontalFlipAug(p=1.1)   # always flip
    _, out = aug(arr, lbl)
    np.testing.assert_allclose(out[0], [0, 0.6, 0.2, 0.9, 0.6], atol=1e-6)
    np.testing.assert_allclose(out[1], [-1] * 5)   # padding untouched


def test_det_random_crop_keeps_coverage():
    import mxnet_tpu.image as img
    rng = np.random.RandomState(0)
    arr = mx.nd.array((rng.rand(40, 40, 3) * 255).astype(np.uint8))
    lbl = np.array([[1, 0.3, 0.3, 0.7, 0.7]], np.float32)
    aug = img.DetRandomCropAug(min_object_covered=0.5, p=1.1)
    out_img, out_lbl = aug(arr, lbl)
    valid = out_lbl[out_lbl[:, 0] >= 0]
    if len(valid):    # crop kept the object: coords still a proper box
        assert (valid[:, 3] > valid[:, 1]).all()
        assert (valid[:, 4] > valid[:, 2]).all()
        assert (valid[:, 1:] >= 0).all() and (valid[:, 1:] <= 1).all()


def test_det_random_pad_shrinks_boxes():
    import mxnet_tpu.image as img
    rng = np.random.RandomState(1)
    arr = mx.nd.array((rng.rand(20, 20, 3) * 255).astype(np.uint8))
    lbl = np.array([[2, 0.2, 0.2, 0.8, 0.8]], np.float32)
    aug = img.DetRandomPadAug(area_range=(2.0, 2.5), p=1.1)
    out_img, out_lbl = aug(arr, lbl)
    h, w = out_img.shape[:2]
    assert h >= 20 and w >= 20 and (h > 20 or w > 20)
    b = out_lbl[0]
    assert b[0] == 2
    assert (b[3] - b[1]) < 0.6 or (b[4] - b[2]) < 0.6   # shrunk


# ---------------------------------------------------------------------------
# SSD end-to-end smoke


def test_ssd_trains_with_finite_decreasing_loss():
    from examples.ssd import train, detect, synthetic_batch
    losses, net = train(epochs=2, steps_per_epoch=4, batch=4, size=64,
                       log=lambda *a: None)
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses
    rng = np.random.RandomState(3)
    imgs, _ = synthetic_batch(2, 64, 3, rng)
    out = detect(net, imgs)
    assert out.shape[0] == 2 and out.shape[2] == 6


def test_bipartite_matching_reference_example():
    """The documented example from contrib/bounding_box.cc:147."""
    s = mx.nd.array(np.array([[0.5, 0.6], [0.1, 0.2], [0.3, 0.4]],
                             np.float32))
    x, y = mx.nd.contrib.bipartite_matching(s, threshold=1e-12,
                                            is_ascend=False)
    np.testing.assert_array_equal(x.asnumpy(), [1, -1, 0])
    np.testing.assert_array_equal(y.asnumpy(), [2, 0])
    # batched + topk
    sb = mx.nd.array(np.stack([s.asnumpy(), s.asnumpy()[::-1]]))
    xb, yb = mx.nd.contrib.bipartite_matching(sb, threshold=1e-12,
                                              topk=1)
    assert xb.shape == (2, 3) and yb.shape == (2, 2)
    assert (xb.asnumpy() >= 0).sum() == 2       # one match per batch
