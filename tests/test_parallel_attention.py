"""Flash attention (Pallas) + ring attention (sequence parallelism).

Mirrors the reference test strategy (SURVEY.md §4): golden forward
against a naive softmax implementation, gradient consistency, causal
masking, ragged lengths; ring attention validated on the 8-device mesh
against the single-device result.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ops.pallas import flash_attention
from mxnet_tpu.parallel.mesh import make_mesh
from mxnet_tpu.parallel.ring_attention import ring_attention, \
    ring_self_attention


def naive_attention(q, k, v, causal=False):
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    if causal:
        m = jnp.tril(jnp.ones((q.shape[2], k.shape[2]), bool))
        s = jnp.where(m, s, -1e30)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)


def _rand_qkv(rng, shape):
    return tuple(jnp.asarray(rng.randn(*shape), jnp.float32)
                 for _ in range(3))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_forward(causal):
    rng = np.random.RandomState(0)
    q, k, v = _rand_qkv(rng, (2, 2, 256, 64))
    o = flash_attention(q, k, v, causal=causal)
    ref = naive_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               rtol=1e-4, atol=2e-5)


def test_flash_attention_ragged_seq():
    rng = np.random.RandomState(1)
    q, k, v = _rand_qkv(rng, (1, 2, 200, 32))
    o = flash_attention(q, k, v, causal=True)
    ref = naive_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               rtol=1e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_grad(causal):
    rng = np.random.RandomState(2)
    q, k, v = _rand_qkv(rng, (1, 2, 128, 32))

    def f(q, k, v):
        return jnp.sum(jnp.sin(flash_attention(q, k, v, causal=causal)))

    def g(q, k, v):
        return jnp.sum(jnp.sin(naive_attention(q, k, v, causal)))

    got = jax.grad(f, (0, 1, 2))(q, k, v)
    ref = jax.grad(g, (0, 1, 2))(q, k, v)
    for a, b in zip(got, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=3e-4)


def test_flash_attention_nd_op():
    rng = np.random.RandomState(3)
    q = mx.nd.array(rng.randn(1, 2, 128, 32).astype(np.float32))
    k = mx.nd.array(rng.randn(1, 2, 128, 32).astype(np.float32))
    v = mx.nd.array(rng.randn(1, 2, 128, 32).astype(np.float32))
    o = mx.nd.contrib.flash_attention(q, k, v, causal=True)
    ref = naive_attention(q._data, k._data, v._data, True)
    np.testing.assert_allclose(o.asnumpy(), np.asarray(ref),
                               rtol=1e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_single_device(causal):
    mesh = make_mesh((8,), axis_names=("sp",))
    rng = np.random.RandomState(4)
    q, k, v = _rand_qkv(rng, (2, 2, 512, 32))
    o = ring_attention(q, k, v, mesh=mesh, causal=causal)
    ref = naive_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               rtol=1e-4, atol=3e-5)


def test_ring_attention_grad():
    mesh = make_mesh((4,), axis_names=("sp",))
    rng = np.random.RandomState(5)
    q, k, v = _rand_qkv(rng, (1, 2, 256, 32))

    def f(q, k, v):
        return jnp.sum(jnp.sin(ring_attention(q, k, v, mesh=mesh,
                                              causal=True)))

    def g(q, k, v):
        return jnp.sum(jnp.sin(naive_attention(q, k, v, True)))

    got = jax.grad(f, (0, 1, 2))(q, k, v)
    ref = jax.grad(g, (0, 1, 2))(q, k, v)
    for a, b in zip(got, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=3e-4)


def test_ring_self_attention_block():
    mesh = make_mesh((8,), axis_names=("sp",))
    rng = np.random.RandomState(6)
    b, s, dm, heads = 2, 256, 64, 4
    x = jnp.asarray(rng.randn(b, s, dm), jnp.float32)
    w_qkv = jnp.asarray(rng.randn(dm, 3 * dm) * 0.05, jnp.float32)
    w_out = jnp.asarray(rng.randn(dm, dm) * 0.05, jnp.float32)
    o = ring_self_attention(x, w_qkv, w_out, heads, mesh=mesh, causal=True)
    assert o.shape == (b, s, dm)
    # reference: same math single-device
    qkv = jnp.einsum("bsd,de->bse", x, w_qkv)
    q, k, v = jnp.split(qkv, 3, -1)

    def hd(t):
        return t.reshape(b, s, heads, dm // heads).transpose(0, 2, 1, 3)

    r = naive_attention(hd(q), hd(k), hd(v), True)
    r = r.transpose(0, 2, 1, 3).reshape(b, s, dm)
    r = jnp.einsum("bsd,de->bse", r, w_out)
    np.testing.assert_allclose(np.asarray(o), np.asarray(r),
                               rtol=1e-4, atol=3e-5)


# ---------------------------------------------------------------------------
# Ulysses (all-to-all) sequence parallelism
# ---------------------------------------------------------------------------

from mxnet_tpu.parallel.ulysses import (ulysses_attention,
                                        ulysses_self_attention)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("impl", ["flash", "einsum"])
def test_ulysses_attention_matches_single_device(causal, impl):
    mesh = make_mesh((8,), axis_names=("sp",))
    rng = np.random.RandomState(7)
    q, k, v = _rand_qkv(rng, (2, 8, 512, 32))
    o = ulysses_attention(q, k, v, mesh=mesh, causal=causal, impl=impl)
    ref = naive_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               rtol=1e-4, atol=3e-5)


def test_ulysses_attention_grad():
    mesh = make_mesh((4,), axis_names=("sp",))
    rng = np.random.RandomState(8)
    q, k, v = _rand_qkv(rng, (1, 4, 256, 32))

    def f(q, k, v):
        return jnp.sum(jnp.sin(ulysses_attention(q, k, v, mesh=mesh,
                                                 causal=True)))

    def g(q, k, v):
        return jnp.sum(jnp.sin(naive_attention(q, k, v, True)))

    got = jax.grad(f, (0, 1, 2))(q, k, v)
    ref = jax.grad(g, (0, 1, 2))(q, k, v)
    for a, b in zip(got, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=3e-4)


def test_ulysses_head_constraint():
    mesh = make_mesh((8,), axis_names=("sp",))
    rng = np.random.RandomState(9)
    q, k, v = _rand_qkv(rng, (1, 4, 256, 16))   # 4 heads < 8 devices
    with pytest.raises(ValueError, match="num_heads"):
        ulysses_attention(q, k, v, mesh=mesh)


def test_ulysses_self_attention_block_matches_ring():
    mesh = make_mesh((8,), axis_names=("sp",))
    rng = np.random.RandomState(10)
    b, s, dm, heads = 2, 256, 64, 8
    x = jnp.asarray(rng.randn(b, s, dm), jnp.float32)
    w_qkv = jnp.asarray(rng.randn(dm, 3 * dm) * 0.05, jnp.float32)
    w_out = jnp.asarray(rng.randn(dm, dm) * 0.05, jnp.float32)
    o_u = ulysses_self_attention(x, w_qkv, w_out, heads, mesh=mesh,
                                 causal=True)
    o_r = ring_self_attention(x, w_qkv, w_out, heads, mesh=mesh,
                              causal=True)
    np.testing.assert_allclose(np.asarray(o_u), np.asarray(o_r),
                               rtol=1e-4, atol=3e-5)
