// C predict ABI for mxnet_tpu.
//
// Capability analog of the reference's standalone inference ABI
// (include/mxnet/c_predict_api.h, src/c_api/c_predict_api.cc): a flat C
// surface a serving process or foreign language binding links against.
//
// TPU-native design: the compute path is XLA, which is only reachable
// through the Python-hosted JAX runtime — so this library EMBEDS
// CPython (Py_Initialize + GIL discipline) and drives the thin
// marshalling helper mxnet_tpu/serving.py. The C side stays a stable
// ~9-function ABI; everything model/shape/dtype-shaped lives behind it.
// cpp-package/include/mxnet_tpu_cpp/predictor.hpp wraps this in C++.
//
// Build: see src/native/Makefile (g++ -shared, python3-config flags).

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#define MXTPU_API extern "C" __attribute__((visibility("default")))

typedef void* PredictorHandle;

namespace {

std::mutex g_err_mutex;
std::string g_last_error;

void set_last_error(const std::string& msg) {
  std::lock_guard<std::mutex> lock(g_err_mutex);
  g_last_error = msg;
}

// Record the active python exception into the error slot.
void capture_py_error() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  std::string msg = "python error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) msg = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  set_last_error(msg);
}

struct Predictor {
  PyObject* obj;  // mxnet_tpu.serving.Predictor instance
};

// Ensure the interpreter is up; returns a GIL guard state.
bool ensure_python(PyGILState_STATE* state) {
  if (!Py_IsInitialized()) {
    // Embedded start: inherit env (MXNET_TPU_PLATFORM etc.)
    Py_InitializeEx(0);
    if (!Py_IsInitialized()) {
      set_last_error("failed to initialize embedded python");
      return false;
    }
    // Release the GIL acquired by initialization so PyGILState works
    // from any caller thread.
    PyEval_SaveThread();
  }
  *state = PyGILState_Ensure();
  return true;
}

}  // namespace

MXTPU_API const char* MXGetLastError() {
  std::lock_guard<std::mutex> lock(g_err_mutex);
  return g_last_error.c_str();
}

// Create a predictor from a symbol json and an mx.nd.save params blob.
// input_shape_indptr/input_shape_data follow the reference's CSR-style
// shape packing (c_predict_api.h MXPredCreate).
MXTPU_API int MXPredCreate(const char* symbol_json_str,
                           const void* param_bytes, int param_size,
                           int dev_type, int dev_id,
                           uint32_t num_input_nodes,
                           const char** input_keys,
                           const uint32_t* input_shape_indptr,
                           const uint32_t* input_shape_data,
                           PredictorHandle* out) {
  PyGILState_STATE gil;
  if (!ensure_python(&gil)) return -1;
  int ret = -1;
  PyObject* mod = nullptr;
  PyObject* cls = nullptr;
  PyObject* shapes = nullptr;
  PyObject* args = nullptr;
  PyObject* obj = nullptr;
  do {
    mod = PyImport_ImportModule("mxnet_tpu.serving");
    if (mod == nullptr) { capture_py_error(); break; }
    cls = PyObject_GetAttrString(mod, "Predictor");
    if (cls == nullptr) { capture_py_error(); break; }
    shapes = PyDict_New();
    for (uint32_t i = 0; i < num_input_nodes; ++i) {
      uint32_t lo = input_shape_indptr[i], hi = input_shape_indptr[i + 1];
      PyObject* shp = PyTuple_New(hi - lo);
      for (uint32_t j = lo; j < hi; ++j) {
        PyTuple_SET_ITEM(shp, j - lo,
                         PyLong_FromUnsignedLong(input_shape_data[j]));
      }
      PyDict_SetItemString(shapes, input_keys[i], shp);
      Py_DECREF(shp);
    }
    PyObject* params = PyBytes_FromStringAndSize(
        static_cast<const char*>(param_bytes), param_size);
    args = Py_BuildValue("(sNiiO)", symbol_json_str, params, dev_type,
                         dev_id, shapes);
    if (args == nullptr) { capture_py_error(); break; }
    obj = PyObject_CallObject(cls, args);
    if (obj == nullptr) { capture_py_error(); break; }
    Predictor* p = new Predictor{obj};
    obj = nullptr;  // ownership moved
    *out = p;
    ret = 0;
  } while (false);
  Py_XDECREF(obj);
  Py_XDECREF(args);
  Py_XDECREF(shapes);
  Py_XDECREF(cls);
  Py_XDECREF(mod);
  PyGILState_Release(gil);
  return ret;
}

MXTPU_API int MXPredSetInput(PredictorHandle handle, const char* key,
                             const float* data, uint32_t size) {
  PyGILState_STATE gil;
  if (!ensure_python(&gil)) return -1;
  Predictor* p = static_cast<Predictor*>(handle);
  PyObject* bytes = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data), size * sizeof(float));
  PyObject* r = PyObject_CallMethod(p->obj, "set_input", "sN", key, bytes);
  int ret = 0;
  if (r == nullptr) { capture_py_error(); ret = -1; }
  Py_XDECREF(r);
  PyGILState_Release(gil);
  return ret;
}

MXTPU_API int MXPredForward(PredictorHandle handle) {
  PyGILState_STATE gil;
  if (!ensure_python(&gil)) return -1;
  Predictor* p = static_cast<Predictor*>(handle);
  PyObject* r = PyObject_CallMethod(p->obj, "forward", nullptr);
  int ret = 0;
  if (r == nullptr) { capture_py_error(); ret = -1; }
  Py_XDECREF(r);
  PyGILState_Release(gil);
  return ret;
}

MXTPU_API int MXPredGetOutputShape(PredictorHandle handle, uint32_t index,
                                   uint32_t* shape_data,
                                   uint32_t* shape_ndim) {
  PyGILState_STATE gil;
  if (!ensure_python(&gil)) return -1;
  Predictor* p = static_cast<Predictor*>(handle);
  PyObject* r = PyObject_CallMethod(p->obj, "get_output_shape", "I", index);
  int ret = -1;
  if (r != nullptr && PyTuple_Check(r)) {
    Py_ssize_t n = PyTuple_Size(r);
    *shape_ndim = static_cast<uint32_t>(n);
    if (shape_data != nullptr) {
      for (Py_ssize_t i = 0; i < n; ++i) {
        shape_data[i] = static_cast<uint32_t>(
            PyLong_AsUnsignedLong(PyTuple_GetItem(r, i)));
      }
    }
    ret = 0;
  } else {
    capture_py_error();
  }
  Py_XDECREF(r);
  PyGILState_Release(gil);
  return ret;
}

MXTPU_API int MXPredGetOutput(PredictorHandle handle, uint32_t index,
                              float* data, uint32_t size) {
  PyGILState_STATE gil;
  if (!ensure_python(&gil)) return -1;
  Predictor* p = static_cast<Predictor*>(handle);
  PyObject* r = PyObject_CallMethod(p->obj, "get_output", "I", index);
  int ret = -1;
  if (r != nullptr && PyBytes_Check(r)) {
    Py_ssize_t n = PyBytes_Size(r);
    if (static_cast<uint32_t>(n) != size * sizeof(float)) {
      set_last_error("output size mismatch");
    } else {
      std::memcpy(data, PyBytes_AsString(r), n);
      ret = 0;
    }
  } else {
    capture_py_error();
  }
  Py_XDECREF(r);
  PyGILState_Release(gil);
  return ret;
}

MXTPU_API int MXPredFree(PredictorHandle handle) {
  PyGILState_STATE gil;
  if (!ensure_python(&gil)) return -1;
  Predictor* p = static_cast<Predictor*>(handle);
  Py_XDECREF(p->obj);
  delete p;
  PyGILState_Release(gil);
  return 0;
}
