"""Gluon utilities.

Reference: python/mxnet/gluon/utils.py (split_data, split_and_load,
clip_global_norm, check_sha1, download).
"""
from __future__ import annotations

import hashlib
import os

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, array

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split an NDArray along an axis into per-device chunks
    (reference: gluon/utils.py split_data)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            "data with shape %s cannot be evenly split into %d slices "
            "along axis %d. Use even_split=False." %
            (data.shape, num_slice, batch_axis))
    step = size // num_slice
    if not even_split:
        slices = []
        for i in range(num_slice):
            begin = i * step
            end = size if i == num_slice - 1 else (i + 1) * step
            slices.append(data.slice_axis(batch_axis, begin, end))
        return slices
    return [data.slice_axis(batch_axis, i * step, (i + 1) * step)
            for i in range(num_slice)]


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split and place per context (reference: gluon/utils.py
    split_and_load)."""
    if not isinstance(data, NDArray):
        data = array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays so the total L2 norm <= max_norm
    (reference: gluon/utils.py clip_global_norm)."""
    import math
    if not arrays:
        raise ValueError("arrays must not be empty")
    total = 0.0
    for a in arrays:
        n = a.norm().asscalar()
        total += float(n) ** 2
    total_norm = math.sqrt(total)
    if check_isfinite and not math.isfinite(total_norm):
        import warnings
        warnings.warn("nan or inf is detected. Clipping results will be "
                      "undefined.", stacklevel=2)
    scale = max_norm / (total_norm + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a *= scale
    return total_norm


def check_sha1(filename, sha1_hash):
    """Reference: gluon/utils.py check_sha1."""
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None,
             retries=5, verify_ssl=True):
    """Download a file (reference: gluon/utils.py download). This build
    runs with zero network egress; only pre-staged files resolve."""
    fname = url.split("/")[-1] if path is None or os.path.isdir(path or ".") \
        else path
    if path and os.path.isdir(path):
        fname = os.path.join(path, fname)
    if os.path.exists(fname) and not overwrite and \
            (not sha1_hash or check_sha1(fname, sha1_hash)):
        return fname
    raise MXNetError(
        "download(%r) unavailable: this environment has no network egress. "
        "Stage the file at %r manually." % (url, fname))
