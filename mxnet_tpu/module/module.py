"""Module: symbol + one bound executor + optimizer state.

Reference: python/mxnet/module/module.py:259-644. The reference's
DataParallelExecutorGroup (executor_group.py:143) slices a batch over a
GPU list; the TPU-native equivalent is sharding the batch over a device
mesh — that path lives in ``mxnet_tpu.kvstore``/``mxnet_tpu.parallel``
(`dist_tpu_sync`), while Module itself binds ONE compiled executor (XLA
distributes over the mesh when the kvstore type asks for it).
"""
from __future__ import annotations

import logging
import warnings

from .. import context as ctx_mod
from .. import optimizer as opt
from ..base import MXNetError
from ..initializer import Uniform, InitDesc
from ..io import DataDesc
from ..model import (_create_kvstore, _initialize_kvstore,
                     _update_params, _update_params_on_kvstore,
                     fused_step_supported, load_checkpoint, BatchEndParam)
from ..ndarray.ndarray import NDArray, zeros
from .base_module import (BaseModule, _check_input_names, _parse_data_desc,
                          _as_list)

__all__ = ["Module"]


class Module(BaseModule):
    """Symbolic training module (reference: module.py:59)."""

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        if context is None:
            context = ctx_mod.current_context()
        if isinstance(context, ctx_mod.Context):
            context = [context]
        self._context = context
        self._work_load_list = work_load_list

        self._symbol = symbol
        data_names = list(data_names) if data_names is not None else []
        label_names = list(label_names) if label_names is not None else []
        state_names = list(state_names) if state_names is not None else []
        fixed_param_names = (list(fixed_param_names)
                             if fixed_param_names is not None else [])
        _check_input_names(symbol, data_names, "data", True)
        _check_input_names(symbol, label_names, "label", False)
        _check_input_names(symbol, state_names, "state", True)
        _check_input_names(symbol, fixed_param_names, "fixed_param", True)

        arg_names = symbol.list_arguments()
        input_names = data_names + label_names + state_names
        self._param_names = [x for x in arg_names if x not in input_names]
        self._fixed_param_names = fixed_param_names
        self._aux_names = symbol.list_auxiliary_states()
        self._data_names = data_names
        self._label_names = label_names
        self._state_names = state_names
        self._output_names = symbol.list_outputs()

        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False
        self._compression_params = compression_params
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._preload_opt_states = None
        self._exec = None
        self._data_shapes = None
        self._label_shapes = None
        self._fused_batch = None
        # >1 after an elastic rescale: each step runs this many
        # sequential gradient microbatches inside the fused program
        # (the per-rank batch is the base world's batch x accum)
        self._elastic_accum = 1

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """Create a module from a saved checkpoint (reference:
        module.py load)."""
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False,
                        nbatch=0, io_cursor=None):
        """Save symbol json + params (+ optimizer states)
        (reference: module.py save_checkpoint → model.py:383).

        Crash-consistent: every file goes through the atomic
        write-temp→fsync→rename path and a ``.manifest.json`` sidecar
        records checksums, epoch/batch position, and RNG state, so a
        SIGKILL at any instant never clobbers the previous good
        checkpoint and ``checkpoint.load_latest_valid`` can verify this
        one. ``nbatch`` > 0 marks a mid-epoch (preemption) save."""
        from .. import telemetry as _tm
        from ..checkpoint import record_checkpoint_save, write_manifest
        t0 = _tm.monotonic()
        sym_file = "%s-symbol.json" % prefix
        self._symbol.save(sym_file)
        param_name = "%s-%04d.params" % (prefix, epoch)
        self.save_params(param_name)
        logging.info("Saved checkpoint to \"%s\"", param_name)
        state_name = None
        if save_optimizer_states:
            state_name = "%s-%04d.states" % (prefix, epoch)
            self.save_optimizer_states(state_name)
            logging.info("Saved optimizer state to \"%s\"", state_name)
        write_manifest(prefix, epoch,
                       {"params": param_name, "symbol": sym_file,
                        "states": state_name}, nbatch=nbatch,
                       extra={"io_cursor": io_cursor} if io_cursor else None)
        record_checkpoint_save(param_name, t0)

    # -- properties --------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        if self._exec.outputs:
            return [(n, tuple(o.shape))
                    for n, o in zip(self._output_names, self._exec.outputs)]
        # before the first forward, infer statically from the bound
        # input shapes (SequentialModule chains shapes at bind time)
        shape_kwargs = {d.name: d.shape for d in self._data_shapes}
        if self._label_shapes:
            shape_kwargs.update({l.name: l.shape
                                 for l in self._label_shapes})
        _, out_shapes, _ = self._symbol.infer_shape(**shape_kwargs)
        if out_shapes is None:
            return None
        return list(zip(self._output_names,
                        [tuple(s) for s in out_shapes]))

    # -- parameters --------------------------------------------------------
    def get_params(self):
        assert self.binded and self.params_initialized
        if self._params_dirty:
            self._sync_params_from_devices()
        return (self._arg_params, self._aux_params)

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        """Initialize parameters (reference: module.py:259)."""
        if self.params_initialized and not force_init:
            warnings.warn("Parameters already initialized and force_init=False. "
                          "init_params call ignored.", stacklevel=2)
            return
        assert self.binded, "call bind before initializing the parameters"

        attrs = self._symbol.attr_dict()

        def _impl(name, arr, cache):
            if cache is not None:
                if name in cache:
                    cache_arr = cache[name]
                    if cache_arr is not arr:
                        arr._set_data(cache_arr._data)
                else:
                    if not allow_missing:
                        raise RuntimeError("%s is not presented" % name)
                    if initializer is not None:
                        initializer(InitDesc(name, attrs.get(name)), arr)
            else:
                if initializer is not None:
                    initializer(InitDesc(name, attrs.get(name)), arr)

        for name in self._param_names:
            _impl(name, self._exec.arg_dict[name], arg_params)
        for name in self._aux_names:
            _impl(name, self._exec.aux_dict[name], aux_params)

        self.params_initialized = True
        self._params_dirty = True
        self._sync_params_from_devices()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        if not allow_missing:
            self.init_params(initializer=None, arg_params=arg_params,
                             aux_params=aux_params,
                             allow_missing=allow_missing,
                             force_init=force_init, allow_extra=allow_extra)
            return
        if self.params_initialized and not force_init:
            warnings.warn("Parameters already initialized and force_init=False. "
                          "set_params call ignored.", stacklevel=2)
            return
        for name, arr in (arg_params or {}).items():
            if name in self._exec.arg_dict:
                self._exec.arg_dict[name]._set_data(arr._data)
        for name, arr in (aux_params or {}).items():
            if name in self._exec.aux_dict:
                self._exec.aux_dict[name]._set_data(arr._data)
        self.params_initialized = True
        self._params_dirty = True

    def _sync_params_from_devices(self):
        """Copy executor parameter values into the CPU-side dicts
        (reference: executor_group get_params)."""
        self._arg_params = {n: self._exec.arg_dict[n].copy()
                            for n in self._param_names}
        self._aux_params = {n: self._exec.aux_dict[n].copy()
                            for n in self._aux_names}
        self._params_dirty = False

    # -- binding -----------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """Bind executors (reference: module.py:364)."""
        if force_rebind:
            self._exec = None
            self.binded = False
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return

        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        assert shared_module is None, \
            "shared_module not supported (XLA shares compiled code by shape)"

        self._data_shapes, self._label_shapes = _parse_data_desc(
            self._data_names, self._label_names, data_shapes, label_shapes)

        shape_kwargs = {d.name: d.shape for d in self._data_shapes}
        if self._label_shapes:
            shape_kwargs.update({l.name: l.shape for l in self._label_shapes})

        reqs = {}
        for name in self._symbol.list_arguments():
            if name in self._param_names:
                reqs[name] = ("null" if name in self._fixed_param_names
                              or not for_training else grad_req)
            elif name in self._data_names:
                reqs[name] = grad_req if inputs_need_grad else "null"
            else:
                reqs[name] = "null"

        ctx = self._context[0]
        type_dict = {}
        for d in self._data_shapes:
            type_dict[d.name] = d.dtype
        if self._label_shapes:
            for l in self._label_shapes:
                type_dict[l.name] = l.dtype
        self._exec = self._symbol.simple_bind(
            ctx, grad_req=reqs, type_dict=type_dict, **shape_kwargs)
        if len(self._context) > 1:
            self._install_dp_mesh()
        self.binded = True

        # re-install cached params into the fresh executor (the reference
        # copies _arg_params into the new exec group at bind, module.py:426)
        if self.params_initialized and self._arg_params is not None:
            self._exec.copy_params_from(self._arg_params, self._aux_params,
                                        allow_extra_params=True)

    def _install_dp_mesh(self):
        """Data-parallel execution over the context list — the
        TPU-native DataParallelExecutorGroup (reference:
        python/mxnet/module/executor_group.py:143): one compiled program
        over a 1-D 'dp' mesh, batch args sharded on dim 0, parameters
        replicated; GSPMD inserts the gradient all-reduce the reference
        ran through KVStore local/device (comm.h:451).

        Raises when the context list cannot be mapped onto distinct
        devices — a context list must never silently train on one
        device."""
        import numpy as np
        from jax.sharding import Mesh
        devices = [c.jax_device() for c in self._context]
        unique = list(dict.fromkeys(devices))
        if len(unique) != len(devices):
            raise MXNetError(
                "Module got %d contexts (%s) but they resolve to only %d "
                "distinct devices; data-parallel binding needs one device "
                "per context. Use fewer contexts or run under more devices."
                % (len(self._context), self._context, len(unique)))
        mesh = Mesh(np.array(unique), ("dp",))
        batch_names = list(self._data_names) + list(self._label_names)
        self._exec.set_dp_mesh(mesh, batch_names)

    def _install_dist_mesh(self, kvstore):
        """Pod-scale data parallelism for ``dist_tpu_sync``: ONE global
        1-D 'dp' mesh over every device of every process (built on the
        same set_dp_mesh machinery the local context-list path uses).
        Each process stages its LOCAL batch shard (per-host input
        sharding — pair the iterator with ``io.dist_parts()``); GSPMD
        folds the cross-host gradient all-reduce into the fused
        train-step program, so the socket parameter server is off the
        hot path entirely."""
        from .. import telemetry as _tm
        from ..parallel.mesh import global_dp_mesh
        mesh = global_dp_mesh()
        batch_names = list(self._data_names) + list(self._label_names)
        self._exec.set_dp_mesh(mesh, batch_names)
        self.logger.info(
            "dist_tpu_sync: global dp mesh over %d devices / %d "
            "processes (rank %d); gradient all-reduce runs in-program",
            mesh.shape["dp"], kvstore.num_workers, kvstore.rank)
        if _tm._enabled:
            _tm.gauge("kvstore/dist_mesh_devices",
                      "Devices in the dist_tpu_sync global dp mesh"
                      ).set(mesh.shape["dp"])

    # -- optimizer ---------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        """Install optimizer + kvstore (reference: module.py:474)."""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring...")
            return
        if self._params_dirty:
            self._sync_params_from_devices()

        (kvstore, update_on_kvstore) = _create_kvstore(
            kvstore, len(self._context), self._arg_params)
        batch_size = self._data_shapes[0].shape[0]
        if kvstore and "dist" in kvstore.type and "_sync" in kvstore.type:
            batch_size *= kvstore.num_workers
        rescale_grad = 1.0 / batch_size

        idx2name = {i: n for i, n in enumerate(self._param_names)}
        if isinstance(optimizer, str):
            optimizer_params = dict(optimizer_params)
            if "rescale_grad" not in optimizer_params:
                optimizer_params["rescale_grad"] = rescale_grad
            optimizer = opt.create(optimizer, sym=self.symbol,
                                   param_idx2name=idx2name,
                                   **optimizer_params)
        else:
            assert isinstance(optimizer, opt.Optimizer)
            if optimizer.rescale_grad != rescale_grad:
                warnings.warn(
                    "Optimizer created manually outside Module but rescale_grad "
                    "is not normalized to 1.0/batch_size/num_workers (%s vs. %s). "
                    "Is this intended?" % (optimizer.rescale_grad, rescale_grad),
                    stacklevel=2)
            if not optimizer.idx2name:
                optimizer.idx2name = idx2name.copy()

        self._optimizer = optimizer
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._updater = None

        if kvstore:
            if self._compression_params:
                kvstore.set_gradient_compression(self._compression_params)
            _initialize_kvstore(kvstore=kvstore,
                                param_arrays=[self._exec.arg_dict[n]
                                              for n in self._param_names],
                                arg_params=self._arg_params,
                                param_names=self._param_names,
                                update_on_kvstore=update_on_kvstore)
            if kvstore.type == "dist_tpu_sync" and kvstore.num_workers > 1:
                # the global mesh makes the backward produce ALREADY
                # all-reduced gradients — only correct when the fused
                # step consumes them in-program. A config the fused
                # path can't take (MXNET_FUSED_STEP=0, optimizer
                # without a pure rule, compression, ...) stays on the
                # per-process local executor: its local gradients ride
                # kvstore.push → _cross_process_allreduce, the
                # host-driven fallback docs/distributed_training.md
                # documents (pushing mesh-reduced gradients through
                # that path would reduce them twice)
                if fused_step_supported(self._optimizer, kvstore,
                                        update_on_kvstore,
                                        self._compression_params) \
                        and self._exec._monitor_callback is None \
                        and not self.inputs_need_grad:
                    self._install_dist_mesh(kvstore)
                else:
                    self.logger.warning(
                        "dist_tpu_sync: configuration cannot take the "
                        "fused in-program-collective step; training "
                        "host-driven (per-gradient device allreduce, "
                        "no socket PS)")
        if update_on_kvstore:
            kvstore.set_optimizer(self._optimizer)
        else:
            self._updater = opt.get_updater(optimizer)

        self.optimizer_initialized = True

        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    # -- computation -------------------------------------------------------
    def _build_feed(self, data_batch):
        """Executor input dict for a DataBatch (shared by the unfused
        forward and the fused train step, so both paths stage identical
        inputs)."""
        feed = {}
        for name, arr in zip(self._data_names, data_batch.data):
            feed[name] = arr
        if self._label_shapes and data_batch.label:
            for name, arr in zip(self._label_names, data_batch.label):
                feed[name] = arr
        return feed

    def forward(self, data_batch, is_train=None):
        """Forward (reference: module.py:589). Reshape-on-the-fly is free:
        jit respecializes per shape signature."""
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        self._exec.forward(is_train=is_train, **self._build_feed(data_batch))
        self._params_dirty = True

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec.backward(out_grads=out_grads)

    # -- fused train step --------------------------------------------------
    def _fused_step_ok(self):
        """True when forward+backward+update may run as ONE donated XLA
        program (Executor.train_step). Falls back for server-side /
        dist_* kvstore updates, gradient compression, optimizers without
        a pure rule, multi-precision, monitors (which need per-op
        outputs), input gradients, and non-'write' grad_req."""
        if not (self.binded and self.params_initialized
                and self.optimizer_initialized):
            return False
        if not fused_step_supported(self._optimizer, self._kvstore,
                                    self._update_on_kvstore,
                                    self._compression_params):
            return False
        if not isinstance(self._updater, opt.Updater):
            return False
        if self._exec._monitor_callback is not None or self.inputs_need_grad:
            return False
        for name in self._param_names:
            if self._exec._grad_req.get(name, "null") not in ("write",
                                                              "null"):
                return False
        return True

    def forward_backward(self, data_batch):
        """Forward + backward; when the fused step is engaged the batch
        is deferred and the whole step (forward, gradients, optimizer
        update) runs as one XLA program inside the following
        ``update()`` call — outputs become available after it, and the
        per-parameter gradient buffers (``_exec.grad_dict``) are NOT
        materialized: gradients exist only inside the program. Reading
        ``get_outputs()`` before ``update()`` replays the batch unfused
        (exact legacy semantics, including grad_dict); code that needs
        host-visible gradients every step should disable the fused path
        (``MXNET_FUSED_STEP=0``)."""
        if not isinstance(data_batch, list) and self._fused_step_ok():
            self._fused_batch = data_batch
            return
        # a batch deferred by an earlier call must not survive into the
        # next update() once the unfused path runs — it would replay the
        # stale batch over this one's gradients
        self._fused_batch = None
        super().forward_backward(data_batch)

    def _run_fused_step(self, data_batch):
        """Execute one fused train step on ``data_batch`` through
        Executor.train_step, keeping the Updater's per-index state dict
        (save/load_optimizer_states) as the source of truth."""
        exe = self._exec
        optimizer = self._optimizer
        updater = self._updater
        feed = self._build_feed(data_batch)
        update_names, states, hyper = [], {}, {}
        for i, name in enumerate(self._param_names):
            if exe._grad_req.get(name, "null") == "null":
                continue
            weight = exe.arg_dict[name]
            update_names.append(name)
            states[name] = opt.fused_state_arrays(
                updater.ensure_state(i, weight))
            hyper[name] = optimizer.fused_hyper(i)
        accum = int(self._elastic_accum)
        if accum > 1:
            # elastic mode: the local batch [A*L, ...] is A microbatches
            # of the BASE world's per-rank batch L, run sequentially
            # inside the program with a fixed accumulation order (the
            # bitwise-continuation contract, see Executor.train_step)
            import numpy as _np
            mb = {}
            for name, arr in feed.items():
                v = arr.asnumpy() if hasattr(arr, "asnumpy") \
                    else _np.asarray(arr)
                if v.shape[0] % accum:
                    raise MXNetError(
                        "elastic accum: batch dim %d of '%s' is not "
                        "divisible by accum factor %d"
                        % (v.shape[0], name, accum))
                mb[name] = v.reshape((accum, v.shape[0] // accum)
                                     + v.shape[1:])
            exe.train_step(optimizer.fused_rule(), tuple(update_names),
                           states, hyper, accum_feed=mb)
        else:
            exe.train_step(optimizer.fused_rule(), tuple(update_names),
                           states, hyper, feed=feed)

    def update(self):
        """Apply optimizer to gradients (reference: module.py:644 →
        model.py _update_params(_on_kvstore)). With a deferred fused
        batch pending, runs the whole step as one program instead."""
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        self._params_dirty = True
        data_batch, self._fused_batch = self._fused_batch, None
        if data_batch is not None:
            if self._fused_step_ok():
                self._run_fused_step(data_batch)
                return
            # configuration changed between forward_backward and update
            # (e.g. fused path disabled): replay the unfused sequence
            self.forward(data_batch, is_train=True)
            self.backward()
        if getattr(self._exec, "_dp_nproc", 1) > 1:
            # the global dist mesh is installed, so these gradients are
            # ALREADY all-reduced by the backward; pushing them through
            # the kvstore would reduce them a second time. Reachable
            # only when the config degraded AFTER init_optimizer gated
            # the mesh install (e.g. a monitor installed mid-training).
            raise MXNetError(
                "dist_tpu_sync: the fused-step configuration changed "
                "after the global mesh was installed (monitor / "
                "grad_req / MXNET_FUSED_STEP?); the unfused update "
                "path cannot run over mesh-reduced gradients — "
                "restore the configuration or set it before "
                "init_optimizer")
        param_arrays = [self._exec.arg_dict[n] for n in self._param_names]
        grad_arrays = [self._exec.grad_dict[n] for n in self._param_names]
        if self._update_on_kvstore:
            _update_params_on_kvstore(param_arrays, grad_arrays,
                                      self._kvstore, self._param_names)
        else:
            _update_params(param_arrays, grad_arrays, updater=self._updater,
                           num_device=len(self._context),
                           kvstore=self._kvstore,
                           param_names=self._param_names)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        if self._fused_batch is not None:
            # a caller inspecting outputs between forward_backward() and
            # update() gets exact legacy semantics: replay the deferred
            # batch unfused (outputs + grads materialize; the following
            # update() takes the legacy per-param path)
            batch, self._fused_batch = self._fused_batch, None
            self.forward(batch, is_train=True)
            self.backward()
        return list(self._exec.outputs)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized \
            and self.inputs_need_grad
        return [self._exec.grad_dict[n] for n in self._data_names]

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        outputs = self.get_outputs()
        if self._elastic_accum > 1 and outputs:
            # accum outputs are stacked [A, world*L, ...]; the metric
            # contract is flat local rows matching the local labels
            # [A*L, ...] — take this host's view and flatten the
            # microbatch dim back into the batch dim
            from ..ndarray.ndarray import array as _arr
            flat = []
            for o in outputs:
                loc = o.asnumpy()
                flat.append(_arr(loc.reshape((-1,) + loc.shape[2:]))
                            if loc.ndim >= 2 else o)
            outputs = flat
        eval_metric.update(labels, outputs)

    def install_monitor(self, mon):
        assert self.binded
        mon.install(self._exec)

    # -- optimizer state io ------------------------------------------------
    def save_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            from ..checkpoint import atomic_writer
            with atomic_writer(fname) as fout:
                fout.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as f:
                self._updater.set_states(f.read())

    def reshape(self, data_shapes, label_shapes=None):
        """Reshape input shapes (reference: module.py reshape). jit
        re-specializes per shape, so only descriptors change."""
        assert self.binded
        self._data_shapes, self._label_shapes = _parse_data_desc(
            self._data_names, self._label_names, data_shapes, label_shapes)

    def borrow_optimizer(self, shared_module):
        assert shared_module.optimizer_initialized
        self._optimizer = shared_module._optimizer
        self._kvstore = shared_module._kvstore
        self._update_on_kvstore = shared_module._update_on_kvstore
        self._updater = shared_module._updater
        self.optimizer_initialized = True

    # -- elastic rescale (checkpoint-free, driven by BaseModule.fit) -------
    def elastic_snapshot(self):
        """Host-side mirror of everything a checkpoint-free rescale
        carries across the runtime teardown: parameters, auxiliary
        states, optimizer state, and the optimizer's schedule counters.
        Pure host copies — after a peer death the device arrays
        (donated into the global mesh) are poisoned, so the
        step-boundary mirror is the only recoverable truth."""
        assert self.binded and self.params_initialized
        exe = self._exec
        snap = {"arg_params": {n: exe.arg_dict[n].asnumpy().copy()
                               for n in self._param_names},
                "aux_params": {n: exe.aux_dict[n].asnumpy().copy()
                               for n in self._aux_names}}
        if self._updater is not None:
            snap["updater"] = self._updater.get_states(dump_optimizer=False)
        if self._optimizer is not None:
            snap["opt_counts"] = dict(self._optimizer._index_update_count)
            snap["num_update"] = int(self._optimizer.num_update)
        return snap

    def elastic_restore(self, snapshot, data_shapes, label_shapes=None,
                        kvstore="dist_tpu_sync", accum=1):
        """Rebuild this module on the CURRENT (post-``dist_runtime.
        reinit``) runtime from an :meth:`elastic_snapshot`: fresh
        executor over the new global mesh, parameters and optimizer
        state from the mirror, gradient-accumulation factor ``accum``.
        The optimizer INSTANCE is kept and its lr-schedule counters are
        restored from the mirror, so the re-executed step sees exactly
        the schedule the unfaulted twin saw."""
        from ..ndarray.ndarray import array as _arr
        optimizer = self._optimizer
        self._elastic_accum = int(accum)
        self._fused_batch = None
        # host mirrors become the bind-time source of truth — the old
        # _arg_params wrap device buffers of the torn-down runtime
        self._arg_params = {k: _arr(v)
                            for k, v in snapshot["arg_params"].items()}
        self._aux_params = {k: _arr(v)
                            for k, v in snapshot["aux_params"].items()}
        self._params_dirty = False
        self.bind(data_shapes=data_shapes, label_shapes=label_shapes,
                  for_training=True, force_rebind=True)
        self.optimizer_initialized = False
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            force_init=True)
        if snapshot.get("updater") is not None and self._updater is not None:
            self._updater.set_states(snapshot["updater"])
        if snapshot.get("opt_counts") is not None and optimizer is not None:
            optimizer._index_update_count = dict(snapshot["opt_counts"])
            optimizer.num_update = int(snapshot.get("num_update",
                                                    optimizer.num_update))
