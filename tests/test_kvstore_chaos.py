"""Cluster chaos suite: self-healing distributed training.

Proves the elastic/failover claims of docs/fault_tolerance.md the same
way PR 4 proved the single-process ones — under *injected* faults:

* fast smokes (tier-1): in-process server failover via snapshot +
  ``restore=True`` with a live client riding through it, dead-rank
  fast-fail for sync rounds and barriers, the ``partition`` fault kind,
  async leave/rejoin membership, straggler telemetry, and the
  barrier/liveness unit contracts (no subprocesses);
* ``slow`` multiprocess chaos: SIGKILL of the server subprocess
  mid-push with a supervised ``--restore`` relaunch (sync run proven
  BITWISE-identical to an unfaulted one), a worker SIGKILLed while
  parked in a barrier (surviving rank gets an MXNetError naming it,
  fast), and async worker death + rejoin converging to the exact
  expected parameters.
"""
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import checkpoint as ckpt
from mxnet_tpu import fault
from mxnet_tpu import telemetry as tm
from mxnet_tpu.base import MXNetError
from mxnet_tpu.kvstore_server import KVStoreServer, recv_msg, send_msg

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_listening(port, timeout=120.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        try:
            s = socket.create_connection(("127.0.0.1", port), timeout=1.0)
            s.close()
            return True
        except OSError:
            time.sleep(0.2)
    return False


def _counter_total(name, label=None):
    fam = tm.REGISTRY._families.get(name)
    if fam is None:
        return 0
    return sum(c.value for lv, c in fam.series()
               if label is None or lv == (label,))


def _gauge_values(name):
    fam = tm.REGISTRY._families.get(name)
    if fam is None:
        return {}
    return {lv: c.value for lv, c in fam.series()}


def _client_env(monkeypatch, port, rank, nw, **extra):
    monkeypatch.setenv("MXNET_TPU_PS_URI", "127.0.0.1")
    monkeypatch.setenv("MXNET_TPU_PS_PORT", str(port))
    monkeypatch.setenv("MXNET_TPU_RANK", str(rank))
    monkeypatch.setenv("MXNET_TPU_NUM_WORKERS", str(nw))
    for k, v in extra.items():
        monkeypatch.setenv(k, str(v))


def _start_restartable(port, **kwargs):
    """Bind-with-retry: the previous incarnation's listener may take a
    moment to release the port."""
    deadline = time.time() + 30.0
    while True:
        try:
            server = KVStoreServer(port=port, **kwargs)
            break
        except OSError:
            if time.time() > deadline:
                raise
            time.sleep(0.2)
    server.start_background()
    return server


# ---------------------------------------------------------------------------
# failover smoke (tier-1): snapshot -> restart -> client rides through
# ---------------------------------------------------------------------------

def _run_push_sequence(monkeypatch, port, pushes):
    kv = mx.kv.create("dist_sync")
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5, momentum=0.9))
    kv.init("w", mx.nd.zeros((4,)))
    for arr in pushes[: len(pushes) // 2]:
        kv.push("w", mx.nd.array(arr))
    return kv


def test_server_failover_snapshot_restore_smoke(tmp_path, monkeypatch):
    """A server restart between pushes is invisible to the client
    beyond a retry: state (weights AND optimizer momentum) restores
    from the snapshot, the client notes the new incarnation, and the
    final weights are bitwise-identical to a never-restarted run."""
    pushes = [np.full((4,), 0.25 * (i + 1), np.float32) for i in range(4)]

    # twin run, no failover: the expected trajectory
    port_t = _free_port()
    twin = _start_restartable(port_t, num_workers=1, sync_mode=True)
    _client_env(monkeypatch, port_t, 0, 1)
    kv_t = _run_push_sequence(monkeypatch, port_t, pushes)
    for arr in pushes[len(pushes) // 2:]:
        kv_t.push("w", mx.nd.array(arr))
    expect = mx.nd.zeros((4,))
    kv_t.pull("w", out=expect)
    kv_t.close()
    twin.stop()

    # failover run: push half, restart the server from its snapshot,
    # push the rest through the SAME client
    snap = str(tmp_path / "kv.snap")
    port = _free_port()
    s1 = _start_restartable(port, num_workers=1, sync_mode=True,
                            snapshot_path=snap)
    _client_env(monkeypatch, port, 0, 1)
    kv = _run_push_sequence(monkeypatch, port, pushes)
    inc1 = kv._server_inc
    assert inc1 == s1.incarnation
    failovers0 = _counter_total("kvstore/server_failovers_total")
    kv._ps_call("STOP")                   # server 1 exits (snapshotted)
    s2 = _start_restartable(port, num_workers=1, sync_mode=True,
                            snapshot_path=snap, restore=True)
    assert s2.incarnation == (s1.incarnation + 1) & 0xFFFFFFFF
    for arr in pushes[len(pushes) // 2:]:
        kv.push("w", mx.nd.array(arr))    # retries ride to server 2
    got = mx.nd.zeros((4,))
    kv.pull("w", out=got)
    assert kv._server_inc == s2.incarnation != inc1
    assert _counter_total("kvstore/server_failovers_total") \
        == failovers0 + 1
    np.testing.assert_array_equal(got.asnumpy(), expect.asnumpy())
    kv.close()
    s2.stop()


def test_restore_with_missing_snapshot_starts_fresh(tmp_path):
    server = KVStoreServer(port=0, num_workers=1, sync_mode=True,
                           snapshot_path=str(tmp_path / "nope.snap"),
                           restore=True)
    assert server._store == {}
    server.stop()


def test_restore_rejects_corrupt_snapshot(tmp_path):
    import struct
    snap = tmp_path / "kv.snap"
    # a zeroed payload header (empty pickle) must not restore silently
    snap.write_bytes(b"MXKVSNAP" + b"\x00" * 64)
    with pytest.raises(MXNetError, match="snapshot"):
        KVStoreServer(port=0, num_workers=1, snapshot_path=str(snap),
                      restore=True)
    # checksum mismatch names the file
    snap.write_bytes(b"MXKVSNAP" + struct.pack("!Q", 10)
                     + struct.pack("!I", 999) + b"x" * 10)
    with pytest.raises(MXNetError, match="checksum"):
        KVStoreServer(port=0, num_workers=1, snapshot_path=str(snap),
                      restore=True)
    # truncation names the byte counts
    snap.write_bytes(b"MXKVSNAP" + struct.pack("!Q", 10)
                     + struct.pack("!I", 0) + b"x" * 3)
    with pytest.raises(MXNetError, match="truncated"):
        KVStoreServer(port=0, num_workers=1, snapshot_path=str(snap),
                      restore=True)


# ---------------------------------------------------------------------------
# dead-rank fast fail (tier-1): error naming the rank, never a hang
# ---------------------------------------------------------------------------

def test_sync_push_dead_rank_fails_fast(monkeypatch):
    server = KVStoreServer(port=0, num_workers=2, sync_mode=True,
                           dead_timeout_s=0.6)
    server.start_background()
    _client_env(monkeypatch, server.port, 0, 2, MXNET_KV_DEAD_S="0.6")
    kv = mx.kv.create("dist_sync")
    kv.init("w", mx.nd.zeros((4,)))
    t0 = time.time()
    with pytest.raises(MXNetError) as ei:
        kv.push("w", mx.nd.ones((4,)))     # rank 1 never shows up
    elapsed = time.time() - t0
    assert "dead" in str(ei.value) and "1" in str(ei.value)
    assert "MXNET_KV_DEAD_S" in str(ei.value)
    assert elapsed < 15.0, "dead-rank detection took %.1fs" % elapsed
    kv.close()
    server.stop()


def test_barrier_dead_rank_fails_fast(monkeypatch):
    server = KVStoreServer(port=0, num_workers=2, sync_mode=True,
                           dead_timeout_s=0.6)
    server.start_background()
    _client_env(monkeypatch, server.port, 0, 2, MXNET_KV_DEAD_S="0.6")
    kv = mx.kv.create("dist_sync")
    t0 = time.time()
    with pytest.raises(MXNetError) as ei:
        kv.barrier()
    elapsed = time.time() - t0
    assert "barrier" in str(ei.value) and "1" in str(ei.value)
    assert elapsed < 15.0
    kv.close()
    server.stop()


def test_barrier_recovers_after_dead_rank_rejoins(monkeypatch):
    """Elasticity, not just fail-fast: once the missing rank shows up,
    the next barrier attempt completes — the failure did not wedge the
    generation counter."""
    server = KVStoreServer(port=0, num_workers=2, sync_mode=True,
                           dead_timeout_s=0.6)
    server.start_background()
    _client_env(monkeypatch, server.port, 0, 2, MXNET_KV_DEAD_S="0.6")
    kv0 = mx.kv.create("dist_sync")
    with pytest.raises(MXNetError):
        kv0.barrier()
    _client_env(monkeypatch, server.port, 1, 2, MXNET_KV_DEAD_S="0.6")
    kv1 = mx.kv.create("dist_sync")
    done = []
    t = threading.Thread(target=lambda: (kv1.barrier(), done.append(1)))
    t.start()
    kv0.barrier()                      # completes: both ranks present
    t.join(timeout=30)
    assert done == [1]
    assert server._barrier_gen == 1
    kv0.close()
    kv1.close()
    server.stop()


# ---------------------------------------------------------------------------
# partition fault kind (tier-1): dropped connection, not an error reply
# ---------------------------------------------------------------------------

def test_partition_drops_connection_and_push_applies_once(monkeypatch):
    server = KVStoreServer(port=0, num_workers=1, sync_mode=True)
    server.start_background()
    _client_env(monkeypatch, server.port, 0, 1)
    monkeypatch.setenv("MXNET_KV_BACKOFF_MS", "20")
    kv = mx.kv.create("dist_sync")
    kv.init("w", mx.nd.zeros((4,)))
    retries0 = _counter_total("kvstore/retries_total")
    fault.arm("kv.server", step=1, kind="partition", count=1)
    try:
        kv.push("w", mx.nd.full((4,), 2.0))
    finally:
        fault.disarm()
    out = mx.nd.zeros((4,))
    kv.pull("w", out=out)
    # applied exactly once despite the dropped-and-resent RPC
    np.testing.assert_array_equal(out.asnumpy(), np.full((4,), 2.0))
    assert _counter_total("kvstore/retries_total") > retries0
    kv.close()
    server.stop()


def test_partition_on_client_reconnect_retries(monkeypatch):
    """kv.client.reconnect partitions are retried like any vanished
    server: the op survives a failed redial."""
    server = KVStoreServer(port=0, num_workers=1, sync_mode=True)
    server.start_background()
    _client_env(monkeypatch, server.port, 0, 1)
    monkeypatch.setenv("MXNET_KV_BACKOFF_MS", "20")
    kv = mx.kv.create("dist_sync")
    kv.init("w", mx.nd.zeros((4,)))
    # first the server drops the connection, then the first redial is
    # itself partitioned — the second redial succeeds
    fault.arm("kv.server", step=1, kind="partition", count=1)
    fault.arm("kv.client.reconnect", step=1, kind="partition", count=1)
    try:
        kv.push("w", mx.nd.ones((4,)))
    finally:
        fault.disarm()
    out = mx.nd.zeros((4,))
    kv.pull("w", out=out)
    np.testing.assert_array_equal(out.asnumpy(), np.ones((4,)))
    kv.close()
    server.stop()


# ---------------------------------------------------------------------------
# elastic membership (tier-1): leave, declare dead, rejoin
# ---------------------------------------------------------------------------

def test_async_worker_leave_and_rejoin_membership(monkeypatch):
    server = KVStoreServer(port=0, num_workers=2, sync_mode=False,
                           dead_timeout_s=0.6)
    server.start_background()
    _client_env(monkeypatch, server.port, 0, 2, MXNET_KV_DEAD_S="0.6")
    kv0 = mx.kv.create("dist_async")
    kv0.set_optimizer(mx.optimizer.SGD(learning_rate=0.5))
    kv0.init("w", mx.nd.zeros((2,)))
    _client_env(monkeypatch, server.port, 1, 2, MXNET_KV_DEAD_S="0.6")
    kv1 = mx.kv.create("dist_async")
    assert kv1.member_epoch == 1
    kv1.init("w", mx.nd.zeros((2,)))       # server's current value wins
    kv1.push("w", mx.nd.ones((2,)))
    kv1.close()                            # rank 1 leaves
    deadline = time.time() + 10
    while kv0.num_dead_node() < 1 and time.time() < deadline:
        time.sleep(0.2)
    assert kv0.num_dead_node() == 1
    # the survivor keeps pushing — async mode never blocks on the dead
    kv0.push("w", mx.nd.ones((2,)))
    rejoins0 = _counter_total("kvstore/worker_rejoins_total", "1")
    kv1b = mx.kv.create("dist_async")      # rank 1 rejoins
    assert kv1b.member_epoch == 2
    assert _counter_total("kvstore/worker_rejoins_total", "1") \
        == rejoins0 + 1
    kv1b.init("w", mx.nd.zeros((2,)))
    kv1b.push("w", mx.nd.ones((2,)))       # resumes contributing
    out = mx.nd.zeros((2,))
    kv1b.pull("w", out=out)
    # three applied updates of -lr*1 each, exactly once each
    np.testing.assert_array_equal(out.asnumpy(), np.full((2,), -1.5))
    kv0.close()
    kv1b.close()
    server.stop()


# ---------------------------------------------------------------------------
# straggler telemetry (tier-1)
# ---------------------------------------------------------------------------

def test_straggler_gauge_per_rank(monkeypatch):
    server = KVStoreServer(port=0, num_workers=2, sync_mode=True)
    server.start_background()

    def _push(rank, delay):
        s = socket.socket()
        s.connect(("127.0.0.1", server.port))
        send_msg(s, ("HELLO", None, rank))
        recv_msg(s)
        if rank == 0:
            send_msg(s, ("INIT", "w", np.zeros((2,), np.float32), 1))
            recv_msg(s)
        time.sleep(delay)
        send_msg(s, ("PUSH", "w", np.ones((2,), np.float32), 2))
        recv_msg(s)
        s.close()

    ts = [threading.Thread(target=_push, args=(0, 0.0)),
          threading.Thread(target=_push, args=(1, 0.4))]
    ts[0].start()
    time.sleep(0.1)     # rank 0's INIT lands before rank 1 pushes
    ts[1].start()
    for t in ts:
        t.join(timeout=30)
    server.stop()
    vals = _gauge_values("kvstore/straggler_seconds")
    assert ("0",) in vals and ("1",) in vals
    assert vals[("1",)] >= 0.2, vals     # rank 1 held the round up
    assert vals[("0",)] <= vals[("1",)]


# ---------------------------------------------------------------------------
# barrier / liveness internals (unit level, no subprocesses)
# ---------------------------------------------------------------------------

def _barrier_client(port, rank, seq, results=None, timeout=30.0):
    s = socket.socket()
    s.settimeout(timeout)
    s.connect(("127.0.0.1", port))
    send_msg(s, ("HELLO", None, rank))
    recv_msg(s)
    send_msg(s, ("BARRIER", None, None, seq))
    resp = recv_msg(s)
    if results is not None:
        results[rank] = resp[0]
    s.close()
    return resp


def test_barrier_generation_increments_once_per_rendezvous():
    server = KVStoreServer(port=0, num_workers=2, sync_mode=True)
    server.start_background()
    for rendezvous, seq in ((1, 1), (2, 2)):
        results = {}
        ts = [threading.Thread(target=_barrier_client,
                               args=(server.port, r, seq, results))
              for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert results == {0: "OK", 1: "OK"}
        assert server._barrier_gen == rendezvous, \
            "generation advanced %d times for %d rendezvous" \
            % (server._barrier_gen, rendezvous)
    server.stop()


def test_stale_reregistration_cannot_resurrect_completed_barrier():
    """After a completed barrier, a rank that re-registers (HELLO) and
    barriers again must WAIT for the other rank — the fresh heartbeat
    plus an old generation must not complete gen N+1 solo or re-notify
    gen N."""
    server = KVStoreServer(port=0, num_workers=2, sync_mode=True)
    server.start_background()
    results = {}
    ts = [threading.Thread(target=_barrier_client,
                           args=(server.port, r, 1, results))
          for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert server._barrier_gen == 1

    # rank 0 re-registers and barriers alone
    s0 = socket.socket()
    s0.settimeout(0.8)
    s0.connect(("127.0.0.1", server.port))
    send_msg(s0, ("HELLO", None, 0))
    recv_msg(s0)
    send_msg(s0, ("BARRIER", None, None, 2))
    with pytest.raises(socket.timeout):
        recv_msg(s0)                    # parked: no resurrection
    assert server._barrier_gen == 1
    # the other rank arrives -> generation 2 completes exactly once
    resp1 = _barrier_client(server.port, 1, 2)
    assert resp1[0] == "OK"
    s0.settimeout(10.0)
    assert recv_msg(s0)[0] == "OK"
    assert server._barrier_gen == 2
    s0.close()
    server.stop()


def test_rank_rpc_dedup_cache_stays_bounded():
    """The at-most-once cache holds ONE entry per rank — an acked RPC
    is evicted the moment the rank's next mutating RPC arrives, so the
    cache cannot grow across epochs."""
    server = KVStoreServer(port=0, num_workers=1, sync_mode=True)
    server.start_background()
    s = socket.socket()
    s.connect(("127.0.0.1", server.port))
    send_msg(s, ("HELLO", None, 0))
    recv_msg(s)
    send_msg(s, ("INIT", "w", np.zeros((2,), np.float32), 1))
    recv_msg(s)
    for seq in range(2, 30):
        send_msg(s, ("PUSH", "w", np.ones((2,), np.float32), seq))
        assert recv_msg(s)[0] == "OK"
    assert len(server._rank_rpc) == 1
    assert server._rank_rpc[0]["seq"] == 29
    s.close()
    server.stop()


# ---------------------------------------------------------------------------
# multiprocess chaos (slow)
# ---------------------------------------------------------------------------

_SERVER_SCRIPT = r"""
import os, sys
marker, port, snap = sys.argv[1], sys.argv[2], sys.argv[3]
if not os.path.exists(marker):
    # first incarnation only: crash inside the commit snapshot of the
    # 6th snapshotting mutation = mid-push of sync round 4
    open(marker, "w").write("armed")
    os.environ["MXNET_FAULT_INJECT"] = "kv.server.snapshot:6:crash"
sys.path.insert(0, %r)
from mxnet_tpu.kvstore_server import serve_forever
serve_forever(["--port", port, "--snapshot", snap, "--restore"])
""" % (REPO,)


def _sync_worker_loop(kv, rank, steps, finals, errors):
    try:
        for s in range(steps):
            grad = np.full((4,), ((s + 1) + 8 * rank) * 0.125,
                           np.float32)
            kv.push("w", mx.nd.array(grad))
        out = mx.nd.zeros((4,))
        kv.pull("w", out=out)
        finals[rank] = out.asnumpy()
    except Exception as e:      # surfaced by the asserting test body
        errors[rank] = e


def _run_sync_cluster(monkeypatch, port, steps):
    """Drive a 2-rank sync training exchange against whatever server
    is at ``port``; returns the final pulled weights per rank."""
    kvs = []
    for rank in range(2):
        _client_env(monkeypatch, port, rank, 2,
                    MXNET_KV_DEAD_S="120",
                    MXNET_KV_RETRIES="60",
                    MXNET_KV_BACKOFF_MS="300",
                    MXNET_KV_TIMEOUT_MS="240000")
        kv = mx.kv.create("dist_sync")
        if rank == 0:
            kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5,
                                              momentum=0.9))
        kv.init("w", mx.nd.zeros((4,)))
        kvs.append(kv)
    finals, errors = {}, {}
    ts = [threading.Thread(target=_sync_worker_loop,
                           args=(kvs[r], r, steps, finals, errors))
          for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=300)
    assert not errors, errors
    assert set(finals) == {0, 1}
    np.testing.assert_array_equal(finals[0], finals[1])
    return kvs, finals[0]


@pytest.mark.slow
def test_chaos_server_sigkill_midpush_restore_bitwise(tmp_path,
                                                      monkeypatch):
    """Acceptance (a): SIGKILL the kvstore server subprocess inside the
    commit snapshot of a mid-training sync round; a supervisor
    relaunches it with --restore; both workers ride the outage on
    retries and the final weights are BITWISE-identical to an unfaulted
    run — no lost, no doubly-applied update."""
    steps = 8

    # unfaulted baseline (in-process server, identical arithmetic)
    base_port = _free_port()
    base = _start_restartable(base_port, num_workers=2, sync_mode=True)
    base_kvs, expect = _run_sync_cluster(monkeypatch, base_port, steps)
    for kv in base_kvs:
        kv.close()
    base.stop()

    # chaos run: server subprocess under the supervisor
    port = _free_port()
    snap = str(tmp_path / "kv.snap")
    marker = str(tmp_path / "crash.marker")
    script = str(tmp_path / "server.py")
    with open(script, "w") as f:
        f.write(_SERVER_SCRIPT)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MXNET_TPU_PS_MODE="sync", MXNET_TPU_NUM_WORKERS="2")
    env.pop("MXNET_TPU_PS_URI", None)
    cmd = [sys.executable, script, marker, str(port), snap]
    sup = {}

    def _supervise():
        sup["rc"] = ckpt.TrainingSupervisor.supervise(
            cmd, max_failures=2, relaunch_delay_s=0.2, env=env)

    t_sup = threading.Thread(target=_supervise, daemon=True)
    t_sup.start()
    assert _wait_listening(port), "server subprocess never came up"

    failovers0 = _counter_total("kvstore/server_failovers_total")
    kvs, got = _run_sync_cluster(monkeypatch, port, steps)
    assert os.path.exists(marker), "crash arming never happened"
    # the crash + supervised relaunch really took place: at least one
    # client observed the incarnation change
    assert _counter_total("kvstore/server_failovers_total") \
        > failovers0, "no failover observed — the fault never fired?"
    kvs[0]._ps_call("STOP")
    for kv in kvs:
        kv.close()
    t_sup.join(timeout=120)
    assert sup.get("rc") == 0, sup
    np.testing.assert_array_equal(got, expect)


_BARRIER_WORKER = r"""
import os, sys
sys.path.insert(0, %r)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import mxnet_tpu as mx
kv = mx.kv.create("dist_sync")
print("ENTERING_BARRIER", flush=True)
kv.barrier()
print("BARRIER_DONE", flush=True)
""" % (REPO,)


@pytest.mark.slow
def test_chaos_worker_sigkill_midbarrier_names_rank(tmp_path,
                                                    monkeypatch):
    """Acceptance (c): a worker SIGKILLed while parked in a dist_sync
    barrier surfaces to the surviving rank as a clear MXNetError naming
    the dead rank within the liveness timeout — never a hang."""
    server = KVStoreServer(port=0, num_workers=2, sync_mode=True,
                           dead_timeout_s=3.0)
    server.start_background()
    script = str(tmp_path / "worker.py")
    with open(script, "w") as f:
        f.write(_BARRIER_WORKER)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MXNET_TPU_PS_URI="127.0.0.1",
               MXNET_TPU_PS_PORT=str(server.port),
               MXNET_TPU_RANK="1", MXNET_TPU_NUM_WORKERS="2",
               MXNET_KV_DEAD_S="3.0")
    proc = subprocess.Popen([sys.executable, script], env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.time() + 120
        while server._barrier_waiting < 1 and time.time() < deadline:
            time.sleep(0.2)
        assert server._barrier_waiting == 1, \
            "worker never reached the barrier"
        proc.kill()                       # SIGKILL while parked
        proc.wait(timeout=30)

        _client_env(monkeypatch, server.port, 0, 2,
                    MXNET_KV_DEAD_S="3.0")
        kv0 = mx.kv.create("dist_sync")
        t0 = time.time()
        with pytest.raises(MXNetError) as ei:
            kv0.barrier()
        elapsed = time.time() - t0
        msg = str(ei.value)
        assert "barrier" in msg and "[1]" in msg and "dead" in msg, msg
        assert elapsed < 3.0 + 10.0, \
            "dead rank surfaced only after %.1fs" % elapsed
        kv0.close()
    finally:
        if proc.poll() is None:
            proc.kill()
        server.stop()


_ASYNC_WORKER = r"""
import os, sys
sys.path.insert(0, %r)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
import mxnet_tpu as mx
n = int(sys.argv[1])
kv = mx.kv.create("dist_async")
kv.init("w", mx.nd.zeros((2,)))     # ignored server-side on rejoin
for i in range(n):
    kv.push("w", mx.nd.ones((2,)))
    print("PUSHED", i + 1, flush=True)
print("WORKER_DONE", flush=True)
""" % (REPO,)


@pytest.mark.slow
def test_chaos_async_worker_death_and_rejoin_converges(tmp_path,
                                                       monkeypatch):
    """Acceptance (b): in dist_async a SIGKILLed worker leaves the
    survivors training; a relaunched worker rejoins (membership epoch
    bumps) and resumes contributing. Every applied update is accounted
    for exactly once: final w = -lr * total_applied_pushes."""
    server = KVStoreServer(port=0, num_workers=2, sync_mode=False,
                           dead_timeout_s=1.0)
    server.start_background()
    _client_env(monkeypatch, server.port, 0, 2, MXNET_KV_DEAD_S="1.0")
    kv0 = mx.kv.create("dist_async")
    kv0.set_optimizer(mx.optimizer.SGD(learning_rate=0.5))
    kv0.init("w", mx.nd.zeros((2,)))

    script = str(tmp_path / "worker.py")
    with open(script, "w") as f:
        f.write(_ASYNC_WORKER)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MXNET_TPU_PS_URI="127.0.0.1",
               MXNET_TPU_PS_PORT=str(server.port),
               MXNET_TPU_RANK="1", MXNET_TPU_NUM_WORKERS="2",
               MXNET_KV_DEAD_S="1.0")
    # first life: crash client-side at the 4th push, BEFORE it is sent
    # -> exactly 3 applied
    env1 = dict(env, MXNET_FAULT_INJECT="kv.push:4:crash")
    p1 = subprocess.run([sys.executable, script, "9"], env=env1,
                        capture_output=True, text=True, timeout=300)
    assert p1.returncode == 137, (p1.returncode, p1.stdout[-500:])
    assert "PUSHED 3" in p1.stdout and "PUSHED 4" not in p1.stdout

    # survivors keep training while rank 1 is dead
    for _ in range(3):
        kv0.push("w", mx.nd.ones((2,)))
    deadline = time.time() + 15
    while kv0.num_dead_node() < 1 and time.time() < deadline:
        time.sleep(0.2)
    assert kv0.num_dead_node() == 1, "rank 1 never declared dead"

    # second life: rejoin and contribute 4 more
    p2 = subprocess.run([sys.executable, script, "4"], env=env,
                        capture_output=True, text=True, timeout=300)
    assert p2.returncode == 0, p2.stdout[-2000:]
    assert "WORKER_DONE" in p2.stdout
    assert server._member_epoch.get(1) == 2, server._member_epoch

    out = mx.nd.zeros((2,))
    kv0.pull("w", out=out)
    # 3 (first life) + 3 (survivor) + 4 (second life) applied once each
    np.testing.assert_array_equal(out.asnumpy(),
                                  np.full((2,), -0.5 * 10))
    kv0.close()
    server.stop()


# ---------------------------------------------------------------------------
# review-hardening regressions
# ---------------------------------------------------------------------------

def test_observational_dead_probe_does_not_declare(monkeypatch):
    """A DEAD_NODES query with a SHORT timeout may report silent ranks
    but must not DECLARE them dead: a later HELLO from such a rank is
    not a rejoin (no membership-epoch bump, no rejoin count)."""
    server = KVStoreServer(port=0, num_workers=1, sync_mode=False,
                           dead_timeout_s=60.0)
    server.start_background()
    _client_env(monkeypatch, server.port, 0, 1)
    kv = mx.kv.create("dist_async")
    time.sleep(0.3)
    rejoins0 = _counter_total("kvstore/worker_rejoins_total", "0")
    # external monitoring probe between heartbeats (raw socket — the
    # rank's own probe RPC would count as live traffic): rank 0 is
    # silent for > 0.1s, so a short-timeout query REPORTS it...
    probe = socket.socket()
    probe.connect(("127.0.0.1", server.port))
    send_msg(probe, ("DEAD_NODES", None, 0.1))
    assert recv_msg(probe)[1] == [0]
    probe.close()
    # ...but does NOT declare it dead (cluster timeout is 60s)
    assert 0 not in server._dead_declared
    kv.close()
    kv2 = mx.kv.create("dist_async")       # reconnect, NOT a rejoin
    assert kv2.member_epoch == 1
    assert _counter_total("kvstore/worker_rejoins_total", "0") == rejoins0
    kv2.close()
    server.stop()


def test_fresh_client_seq_base_cannot_collide_with_predecessor(
        monkeypatch):
    """A restarted worker is a fresh client whose seq counter restarts;
    seqs start from a random per-client base so its first mutating RPC
    can never match a predecessor's commit record and be swallowed as a
    duplicate."""
    server = KVStoreServer(port=0, num_workers=1, sync_mode=True)
    server.start_background()
    _client_env(monkeypatch, server.port, 0, 1)
    kv_a = mx.kv.create("dist_sync")
    kv_a.init("w", mx.nd.zeros((2,)))      # commits seq base_a+1
    committed = server._applied_seq[0]
    kv_a.close()
    kv_b = mx.kv.create("dist_sync")       # the relaunched worker
    assert kv_b._seq != kv_a._seq
    assert kv_b._seq > (1 << 16)           # randomized base, not 0
    # its first mutating RPC executes for real (store mutates), it is
    # not replayed from the predecessor's cached ack
    kv_b.init("x", mx.nd.ones((2,)))
    assert server._applied_seq[0] != committed
    out = mx.nd.zeros((2,))
    kv_b.pull("x", out=out)
    np.testing.assert_array_equal(out.asnumpy(), np.ones((2,)))
    kv_b.close()
    server.stop()


def test_closed_kvstore_is_terminal(monkeypatch):
    """close() must not silently resurrect the connection on the next
    op — a revived client would run with no heartbeat and read as a
    dead rank mid-round. Ops on a closed store raise."""
    server = KVStoreServer(port=0, num_workers=1, sync_mode=True)
    server.start_background()
    _client_env(monkeypatch, server.port, 0, 1)
    kv = mx.kv.create("dist_sync")
    kv.init("w", mx.nd.zeros((2,)))
    kv.close()
    with pytest.raises(MXNetError, match="closed"):
        kv.push("w", mx.nd.ones((2,)))
    server.stop()


def test_stop_aborts_parked_sync_round_no_false_ack(monkeypatch):
    """STOP while a worker is parked in an incomplete sync round must
    NOT ack its push as OK (the update was never applied or
    snapshotted): the waiter gets a retryable abort, which surfaces as
    a clear error when no successor server appears."""
    server = KVStoreServer(port=0, num_workers=2, sync_mode=True,
                           dead_timeout_s=60.0)
    server.start_background()
    _client_env(monkeypatch, server.port, 0, 2,
                MXNET_KV_RETRIES="1", MXNET_KV_BACKOFF_MS="20",
                MXNET_KV_TIMEOUT_MS="5000")
    kv = mx.kv.create("dist_sync")
    kv.init("w", mx.nd.zeros((2,)))
    result = {}

    def _push():
        try:
            kv.push("w", mx.nd.ones((2,)))
            result["outcome"] = "ok"
        except MXNetError as e:
            result["outcome"] = "error"
            result["msg"] = str(e)

    t = threading.Thread(target=_push)
    t.start()
    deadline = time.time() + 10
    while not server._pending and time.time() < deadline:
        time.sleep(0.05)
    assert server._pending, "push never parked"
    stopper = socket.socket()
    stopper.connect(("127.0.0.1", server.port))
    send_msg(stopper, ("STOP", None, None))
    recv_msg(stopper)
    stopper.close()
    t.join(timeout=30)
    assert result.get("outcome") == "error", result
    kv.close()


def test_restore_rejects_changed_cluster_shape(tmp_path):
    """--restore under a different mode or world size raises a clear
    error naming both values instead of mixing incompatible state."""
    snap = str(tmp_path / "kv.snap")
    s1 = KVStoreServer(port=0, num_workers=2, sync_mode=True,
                       snapshot_path=snap)
    s1.start_background()
    sock = socket.socket()
    sock.connect(("127.0.0.1", s1.port))
    send_msg(sock, ("HELLO", None, 0))
    recv_msg(sock)
    send_msg(sock, ("INIT", "w", np.zeros((2,), np.float32), 1))
    assert recv_msg(sock)[0] == "OK"       # snapshots on new-key INIT
    sock.close()
    s1.stop()
    with pytest.raises(MXNetError, match="num_workers=2"):
        KVStoreServer(port=0, num_workers=3, sync_mode=True,
                      snapshot_path=snap, restore=True)
    with pytest.raises(MXNetError, match="mode"):
        KVStoreServer(port=0, num_workers=2, sync_mode=False,
                      snapshot_path=snap, restore=True)
    # the matching shape still restores
    s2 = KVStoreServer(port=0, num_workers=2, sync_mode=True,
                       snapshot_path=snap, restore=True)
    assert "w" in s2._store
    s2.stop()


def test_closed_store_guards_every_ps_op(monkeypatch):
    """barrier/num_dead_node/set_optimizer must refuse on a closed
    store, not silently fall back to local/jax semantics."""
    server = KVStoreServer(port=0, num_workers=2, sync_mode=True)
    server.start_background()
    _client_env(monkeypatch, server.port, 0, 2)
    kv = mx.kv.create("dist_sync")
    kv.close()
    with pytest.raises(MXNetError, match="closed"):
        kv.barrier()
    with pytest.raises(MXNetError, match="closed"):
        kv.num_dead_node()
    with pytest.raises(MXNetError, match="closed"):
        kv.set_optimizer(mx.optimizer.SGD())
    server.stop()


def test_pure_async_rejoin_detected_without_observer(monkeypatch):
    """With NO sync waiter and NO DEAD_NODES probe ever running, a rank
    that re-registers after silence past the liveness bound is still
    recognized as a rejoin (epoch bump) — the HELLO itself compares the
    silence age."""
    server = KVStoreServer(port=0, num_workers=2, sync_mode=False,
                           dead_timeout_s=0.5)
    server.start_background()
    _client_env(monkeypatch, server.port, 1, 2, MXNET_KV_DEAD_S="0.5")
    kv1 = mx.kv.create("dist_async")
    assert kv1.member_epoch == 1
    kv1.close()
    time.sleep(0.8)                        # outlive the bound, unobserved
    kv1b = mx.kv.create("dist_async")
    assert kv1b.member_epoch == 2
    kv1b.close()
    server.stop()
