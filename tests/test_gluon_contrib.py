"""gluon.contrib: Concurrent/Identity/SparseEmbedding/SyncBatchNorm,
VariationalDropout/LSTMP/Conv*Cells, IntervalSampler (reference:
python/mxnet/gluon/contrib/)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.gluon import nn as gnn
from mxnet_tpu.gluon.contrib import data as cdata, nn as cnn, rnn as crnn
from mxnet_tpu.gluon.rnn import LSTMCell


def test_concurrent_and_identity():
    rng = np.random.RandomState(0)
    net = cnn.HybridConcurrent(axis=1)
    net.add(gnn.Dense(4), cnn.Identity(), gnn.Dense(2))
    net.initialize()
    net.hybridize()
    x = nd.array(rng.randn(3, 5).astype(np.float32))
    out = net(x)
    assert out.shape == (3, 11)          # 4 + 5 + 2
    # Identity slice equals the input
    np.testing.assert_allclose(out.asnumpy()[:, 4:9], x.asnumpy(),
                               rtol=1e-6)

    eager = cnn.Concurrent(axis=-1)
    eager.add(cnn.Identity(), cnn.Identity())
    eager.initialize()
    np.testing.assert_allclose(eager(x).asnumpy(),
                               np.concatenate([x.asnumpy()] * 2, -1))


def test_sparse_embedding_row_sparse_grad():
    emb = cnn.SparseEmbedding(40, 6)
    emb.initialize()
    idx = nd.array(np.array([1, 3, 3, 7], np.float32))
    with autograd.record():
        loss = (emb(idx) ** 2).sum()
    loss.backward()
    g = emb.weight.grad()
    assert g.stype == "row_sparse"
    rows = set(int(i) for i in np.asarray(g.indices))
    assert rows == {1, 3, 7}
    # dense equivalence
    w = emb.weight.data().asnumpy()
    dense = np.zeros_like(w)
    for i in [1, 3, 3, 7]:
        dense[i] += 2 * w[i]
    np.testing.assert_allclose(g.todense().asnumpy(), dense, rtol=1e-5,
                               atol=1e-6)


def test_contrib_sync_batch_norm_layer():
    net = cnn.SyncBatchNorm(num_devices=1)
    net.initialize()
    x = nd.array(np.random.RandomState(1).randn(4, 3, 5, 5)
                 .astype(np.float32))
    with autograd.record():
        y = net(x)
    # per-channel train-mode output is standardized
    m = y.asnumpy().mean(axis=(0, 2, 3))
    v = y.asnumpy().var(axis=(0, 2, 3))
    np.testing.assert_allclose(m, 0, atol=1e-5)
    np.testing.assert_allclose(v, 1, atol=1e-3)


def test_variational_dropout_locks_mask():
    vd = crnn.VariationalDropoutCell(LSTMCell(6), drop_inputs=0.5,
                                     drop_outputs=0.5)
    vd.initialize()
    x = nd.array(np.ones((3, 7, 5), np.float32))
    with autograd.record():
        out, _ = vd.unroll(7, x, merge_outputs=True)
    zp = (out.asnumpy() == 0)
    assert zp.any()
    assert (zp[:, 0:1] == zp).all()      # identical zero pattern per step


def test_lstmp_projection():
    cell = crnn.LSTMPCell(hidden_size=8, projection_size=3)
    cell.initialize()
    rng = np.random.RandomState(2)
    out, states = cell.unroll(
        4, nd.array(rng.randn(2, 4, 5).astype(np.float32)),
        merge_outputs=True)
    assert out.shape == (2, 4, 3)
    assert states[0].shape == (2, 3) and states[1].shape == (2, 8)


@pytest.mark.parametrize("kind,n_states", [("RNN", 1), ("LSTM", 2),
                                           ("GRU", 1)])
def test_conv2d_cells(kind, n_states):
    cls = getattr(crnn, "Conv2D%sCell" % kind)
    cell = cls(input_shape=(2, 6, 6), hidden_channels=4, i2h_kernel=3,
               h2h_kernel=3, i2h_pad=1)
    cell.initialize()
    rng = np.random.RandomState(3)
    seq = nd.array(rng.randn(2, 5, 2, 6, 6).astype(np.float32))
    with autograd.record():
        outs, states = cell.unroll(5, seq, merge_outputs=True)
        loss = (outs ** 2).sum()
    loss.backward()
    assert outs.shape == (2, 5, 4, 6, 6)
    assert len(states) == n_states
    g = cell.i2h_weight.grad()
    assert np.isfinite(g.asnumpy()).all() and np.abs(g.asnumpy()).sum() > 0


def test_conv1d_3d_cells_shapes():
    c1 = crnn.Conv1DLSTMCell(input_shape=(2, 8), hidden_channels=3,
                             i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
    c1.initialize()
    o1, _ = c1.unroll(3, nd.array(np.random.rand(1, 3, 2, 8)
                                  .astype(np.float32)),
                      merge_outputs=True)
    assert o1.shape == (1, 3, 3, 8)
    c3 = crnn.Conv3DGRUCell(input_shape=(1, 4, 4, 4), hidden_channels=2,
                            i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
    c3.initialize()
    o3, _ = c3.unroll(2, nd.array(np.random.rand(1, 2, 1, 4, 4, 4)
                                  .astype(np.float32)),
                      merge_outputs=True)
    assert o3.shape == (1, 2, 2, 4, 4, 4)


def test_interval_sampler():
    s = cdata.IntervalSampler(10, 3)
    assert list(s) == [0, 3, 6, 9, 1, 4, 7, 2, 5, 8]
    assert len(s) == 10
    s2 = cdata.IntervalSampler(10, 3, rollover=False)
    assert list(s2) == [0, 3, 6, 9]
    assert len(s2) == 4
