"""Elementwise operators.

TPU-native replacement for the reference's elementwise op families
(reference: src/operator/tensor/elemwise_unary_op_basic.cc,
elemwise_binary_broadcast_op_*.cc, elemwise_binary_scalar_op_*.cc and the
scalar functor zoo in src/operator/mshadow_op.h). Each op is a pure jnp
function; XLA fuses chains of these into single kernels, which replaces
the reference's engine-level op bulking (SURVEY.md §2.2).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias

# ---------------------------------------------------------------------------
# unary
# ---------------------------------------------------------------------------

def _softrelu(x):
    return jnp.logaddexp(x, 0.0)


_UNARY = {
    "abs": jnp.abs,
    "sign": jnp.sign,
    "rint": jnp.rint,
    "round": jnp.round,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "trunc": jnp.trunc,
    "fix": jnp.trunc,
    "square": jnp.square,
    "sqrt": jnp.sqrt,
    "rsqrt": lambda x: lax.rsqrt(x),
    "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "exp": jnp.exp,
    "log": jnp.log,
    "log10": jnp.log10,
    "log2": jnp.log2,
    "log1p": jnp.log1p,
    "expm1": jnp.expm1,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
    "negative": jnp.negative,
    "reciprocal": lambda x: 1.0 / x,
    "relu": lambda x: jnp.maximum(x, 0),
    "sigmoid": jax.nn.sigmoid,
    "softsign": jax.nn.soft_sign,
    "softrelu": _softrelu,
    "erf": jax.scipy.special.erf,
    "erfinv": jax.scipy.special.erfinv,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": jax.scipy.special.gammaln,
    "logical_not": lambda x: (x == 0).astype(x.dtype),
}

for _name, _f in _UNARY.items():
    register(_name)(lambda x, _f=_f: _f(x))

@register("_copy")
def _copy(x):
    return x

alias("identity", "_copy")


@register("stop_gradient")
def _stop_gradient(x):
    return lax.stop_gradient(x)

alias("BlockGrad", "stop_gradient")


@register("make_loss")
def _make_loss(x):
    return x

alias("MakeLoss", "make_loss")


# ---------------------------------------------------------------------------
# binary (broadcasting); elemwise_* are the same-shape fast path in the
# reference (src/operator/tensor/elemwise_binary_op_basic.cc) — on XLA both
# lower identically, so they share implementations.
# ---------------------------------------------------------------------------

def _cmp(f):
    def _g(a, b):
        return f(a, b).astype(jnp.result_type(a, b))
    return _g


_BINARY = {
    "broadcast_add": jnp.add,
    "broadcast_sub": jnp.subtract,
    "broadcast_mul": jnp.multiply,
    "broadcast_div": jnp.divide,
    "broadcast_mod": jnp.mod,
    "broadcast_power": jnp.power,
    "broadcast_maximum": jnp.maximum,
    "broadcast_minimum": jnp.minimum,
    "broadcast_hypot": jnp.hypot,
    "broadcast_equal": _cmp(jnp.equal),
    "broadcast_not_equal": _cmp(jnp.not_equal),
    "broadcast_greater": _cmp(jnp.greater),
    "broadcast_greater_equal": _cmp(jnp.greater_equal),
    "broadcast_lesser": _cmp(jnp.less),
    "broadcast_lesser_equal": _cmp(jnp.less_equal),
    "broadcast_logical_and": _cmp(jnp.logical_and),
    "broadcast_logical_or": _cmp(jnp.logical_or),
    "broadcast_logical_xor": _cmp(jnp.logical_xor),
    "arctan2": jnp.arctan2,
}

# indicator-valued ops: gradient is zero by contract (the reference
# registers them without FGradient), so they are non-differentiable
_INDICATOR = {"broadcast_equal", "broadcast_not_equal", "broadcast_greater",
              "broadcast_greater_equal", "broadcast_lesser",
              "broadcast_lesser_equal", "broadcast_logical_and",
              "broadcast_logical_or", "broadcast_logical_xor"}

for _name, _f in _BINARY.items():
    register(_name, differentiable=_name not in _INDICATOR)(
        lambda a, b, _f=_f: _f(a, b))

for _ew, _bc in [("elemwise_add", "broadcast_add"), ("elemwise_sub", "broadcast_sub"),
                 ("elemwise_mul", "broadcast_mul"), ("elemwise_div", "broadcast_div"),
                 ("_plus", "broadcast_add"), ("_minus", "broadcast_sub"),
                 ("_mul", "broadcast_mul"), ("_div", "broadcast_div"),
                 ("_add", "broadcast_add"), ("_sub", "broadcast_sub"),
                 ("_maximum", "broadcast_maximum"), ("_minimum", "broadcast_minimum"),
                 ("_power", "broadcast_power"), ("_mod", "broadcast_mod"),
                 ("_equal", "broadcast_equal"), ("_not_equal", "broadcast_not_equal"),
                 ("_greater", "broadcast_greater"), ("_greater_equal", "broadcast_greater_equal"),
                 ("_lesser", "broadcast_lesser"), ("_lesser_equal", "broadcast_lesser_equal"),
                 ("_hypot", "broadcast_hypot")]:
    alias(_ew, _bc)


# ---------------------------------------------------------------------------
# binary with scalar attr (reference: src/operator/tensor/elemwise_binary_scalar_op_*.cc)
# ---------------------------------------------------------------------------

def _scalar_op(name, f, defaults=None, differentiable=True):
    def _g(x, scalar=0.0):
        return f(x, jnp.asarray(scalar, dtype=x.dtype))
    register(name, attr_defaults=(defaults or {"scalar": 0.0}),
             differentiable=differentiable)(_g)


_scalar_op("_plus_scalar", jnp.add)
_scalar_op("_minus_scalar", jnp.subtract)
_scalar_op("_rminus_scalar", lambda x, s: s - x)
_scalar_op("_mul_scalar", jnp.multiply)
_scalar_op("_div_scalar", jnp.divide)
_scalar_op("_rdiv_scalar", lambda x, s: s / x)
_scalar_op("_mod_scalar", jnp.mod)
_scalar_op("_rmod_scalar", lambda x, s: jnp.mod(s, x))
_scalar_op("_power_scalar", jnp.power)
_scalar_op("_rpower_scalar", lambda x, s: jnp.power(s, x))
_scalar_op("_maximum_scalar", jnp.maximum)
_scalar_op("_minimum_scalar", jnp.minimum)
_scalar_op("_hypot_scalar", jnp.hypot)
_scalar_op("_equal_scalar", _cmp(jnp.equal), differentiable=False)
_scalar_op("_not_equal_scalar", _cmp(jnp.not_equal), differentiable=False)
_scalar_op("_greater_scalar", _cmp(jnp.greater), differentiable=False)
_scalar_op("_greater_equal_scalar", _cmp(jnp.greater_equal), differentiable=False)
_scalar_op("_lesser_scalar", _cmp(jnp.less), differentiable=False)
_scalar_op("_lesser_equal_scalar", _cmp(jnp.less_equal), differentiable=False)
_scalar_op("_logical_and_scalar", _cmp(jnp.logical_and), differentiable=False)
_scalar_op("_logical_or_scalar", _cmp(jnp.logical_or), differentiable=False)
_scalar_op("_logical_xor_scalar", _cmp(jnp.logical_xor), differentiable=False)


@register("clip", attr_defaults={"a_min": 0.0, "a_max": 0.0})
def _clip(x, a_min=0.0, a_max=0.0):
    return jnp.clip(x, a_min, a_max)


@register("smooth_l1", attr_defaults={"scalar": 1.0})
def _smooth_l1(x, scalar=1.0):
    """Reference: src/operator/tensor/elemwise_binary_scalar_op_extended.cc."""
    s2 = scalar * scalar
    absx = jnp.abs(x)
    return jnp.where(absx < 1.0 / s2, 0.5 * s2 * x * x, absx - 0.5 / s2)


@register("zeros_like")
def _zeros_like(x):
    return jnp.zeros_like(x)


@register("ones_like")
def _ones_like(x):
    return jnp.ones_like(x)


@register("shape_array", differentiable=False)
def _shape_array(x):
    return jnp.asarray(x.shape, dtype=jnp.int32)


@register("size_array", differentiable=False)
def _size_array(x):
    return jnp.asarray([x.size], dtype=jnp.int32)


@register("Cast", attr_defaults={"dtype": "float32"})
def _cast(x, dtype="float32"):
    from ..base import np_dtype
    return x.astype(np_dtype(dtype))

alias("cast", "Cast")


@register("hard_sigmoid", attr_defaults={"alpha": 0.2, "beta": 0.5})
def _hard_sigmoid(x, alpha=0.2, beta=0.5, **_ig):
    """y = max(0, min(1, alpha*x + beta)) (reference:
    tensor/elemwise_unary_op_basic.cc:109)."""
    return jnp.clip(alpha * x + beta, 0.0, 1.0)


# ---------------------------------------------------------------------------
# logical binary family (reference: elemwise_binary_op_logic.cc,
# elemwise_binary_scalar_op_logic.cc) — outputs are 0/1 in the input
# dtype, like the comparison family
# ---------------------------------------------------------------------------

def _logical_family(name, fn):
    """Elemwise twins of the broadcast_logical_* family above
    (reference: elemwise_binary_op_logic.cc registers both; the scalar
    variants are registered with the scalar sweep at line ~186)."""
    @register("_" + name, differentiable=False)
    def _op(a, b, _fn=fn):
        return _fn(a != 0, b != 0).astype(a.dtype)
    alias(name, "_" + name)


_logical_family("logical_and", jnp.logical_and)
_logical_family("logical_or", jnp.logical_or)
_logical_family("logical_xor", jnp.logical_xor)


@register("add_n")
def _add_n(*args, **_ig):
    """Variadic sum (reference: elemwise_sum.cc ElementWiseSum — the
    gradient-aggregation workhorse). XLA fuses the chain."""
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


alias("ElementWiseSum", "add_n")


@register("SoftmaxActivation", attr_defaults={"mode": "instance"})
def _softmax_activation(x, mode="instance", **_ig):
    """Deprecated-but-present reference op
    (src/operator/softmax_activation.cc): softmax over the class axis
    ('instance') or per spatial position over channels ('channel')."""
    import jax
    if mode == "channel":
        return jax.nn.softmax(x, axis=1)
    return jax.nn.softmax(x.reshape(x.shape[0], -1),
                          axis=-1).reshape(x.shape)


# reference add_alias parity (elemwise_binary_broadcast_op_basic.cc)
alias("broadcast_plus", "broadcast_add")
alias("broadcast_minus", "broadcast_sub")
