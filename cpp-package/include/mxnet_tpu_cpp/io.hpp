// C++ data-iterator wrapper over the general C ABI.
// Capability analog of the reference's cpp-package/include/mxnet-cpp/
// io.h MXDataIter: create a registered iterator by name with flat
// string kwargs, walk epochs batch by batch.
#ifndef MXNET_TPU_CPP_IO_HPP_
#define MXNET_TPU_CPP_IO_HPP_

#include <map>
#include <string>
#include <vector>

#include "mxnet_tpu_cpp/ndarray.hpp"

namespace mxnet_tpu_cpp {

inline std::vector<std::string> ListDataIters() {
  uint32_t n = 0;
  const char** names = nullptr;
  Check(MXListDataIters(&n, &names));
  return std::vector<std::string>(names, names + n);
}

class DataIter {
 public:
  DataIter(const std::string& name,
           const std::map<std::string, std::string>& kwargs) {
    std::vector<const char*> ks, vs;
    for (const auto& kv : kwargs) {
      ks.push_back(kv.first.c_str());
      vs.push_back(kv.second.c_str());
    }
    Check(MXDataIterCreateIter(name.c_str(),
                               static_cast<uint32_t>(ks.size()),
                               ks.data(), vs.data(), &handle_));
  }

  DataIter(const DataIter&) = delete;
  DataIter& operator=(const DataIter&) = delete;

  ~DataIter() {
    if (handle_ != nullptr) MXDataIterFree(handle_);
  }

  bool Next() {
    int has = 0;
    Check(MXDataIterNext(handle_, &has));
    return has != 0;
  }

  void Reset() { Check(MXDataIterBeforeFirst(handle_)); }

  NDArray Data() const {
    NDArrayHandle h = nullptr;
    Check(MXDataIterGetData(handle_, &h));
    return NDArray::FromHandle(h);
  }

  NDArray Label() const {
    NDArrayHandle h = nullptr;
    Check(MXDataIterGetLabel(handle_, &h));
    return NDArray::FromHandle(h);
  }

  int PadNum() const {
    int pad = 0;
    Check(MXDataIterGetPadNum(handle_, &pad));
    return pad;
  }

 private:
  DataIterHandle handle_ = nullptr;
};

}  // namespace mxnet_tpu_cpp

#endif  // MXNET_TPU_CPP_IO_HPP_
