// A C++ client training a linear model through the general C ABI.
//
// Capability analog of the reference's cpp-package training examples
// (cpp-package/example/*.cpp over include/mxnet-cpp): NDArray CRUD,
// autograd record/backward, generated op wrappers, in-place optimizer
// update — all via include/mxnet_tpu/c_api.h, no Python in this file.
//
// Build + run: see tests/test_c_api.py.
#include <cmath>
#include <cstdio>
#include <vector>

#include "mxnet_tpu_cpp/ndarray.hpp"
#include "mxnet_tpu_cpp/op.h"

using mxnet_tpu_cpp::AutogradRecord;
using mxnet_tpu_cpp::Invoke;
using mxnet_tpu_cpp::InvokeInPlace;
using mxnet_tpu_cpp::NDArray;

int main() {
  const uint32_t kN = 64, kD = 3;
  // synthetic data: y = X @ [2, -1, 0.5]
  std::vector<float> xs(kN * kD), ys(kN);
  unsigned seed = 12345;
  auto frand = [&seed]() {
    seed = seed * 1103515245u + 12345u;
    return ((seed >> 16) & 0x7fff) / 32768.0f - 0.5f;
  };
  const float w_true[kD] = {2.0f, -1.0f, 0.5f};
  for (uint32_t i = 0; i < kN; ++i) {
    float dot = 0.0f;
    for (uint32_t j = 0; j < kD; ++j) {
      xs[i * kD + j] = frand();
      dot += xs[i * kD + j] * w_true[j];
    }
    ys[i] = dot;
  }

  NDArray X({kN, kD});
  NDArray Y({kN, 1});
  X.CopyFrom(xs);
  Y.CopyFrom(ys);

  NDArray w({kD, 1});
  std::vector<float> w0(kD, 0.0f);
  w.CopyFrom(w0);
  w.AttachGrad();

  float loss_val = 0.0f;
  for (int step = 0; step < 120; ++step) {
    NDArray loss;
    {
      AutogradRecord rec;
      NDArray pred = mxnet_tpu_cpp::op::dot(X, w);
      NDArray err = mxnet_tpu_cpp::op::elemwise_sub(pred, Y);
      NDArray sq = mxnet_tpu_cpp::op::square(err);
      loss = mxnet_tpu_cpp::op::mean(sq);
    }
    loss.Backward();
    NDArray g = w.Grad();
    InvokeInPlace("sgd_update", {&w, &g},
                  {{"lr", "0.5"}, {"wd", "0.0"}});
    loss_val = loss.CopyTo()[0];
  }

  std::vector<float> w_out = w.CopyTo();
  std::printf("loss %.6f\n", loss_val);
  std::printf("w %.4f %.4f %.4f\n", w_out[0], w_out[1], w_out[2]);
  for (uint32_t j = 0; j < kD; ++j) {
    if (std::fabs(w_out[j] - w_true[j]) > 0.05f) {
      std::printf("FAIL: w[%u]=%.4f expect %.4f\n", j, w_out[j],
                  w_true[j]);
      return 1;
    }
  }
  std::printf("TRAIN OK\n");
  return 0;
}
