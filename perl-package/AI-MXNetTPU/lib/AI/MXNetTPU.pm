package AI::MXNetTPU;

# Perl binding for mxnet_tpu inference (capability analog of the
# reference's perl-package AI::MXNet, scoped to the predict ABI as the
# cheap-binding proof the flat C surface is designed for).
#
#   my $pred = AI::MXNetTPU::Predictor->new(
#       symbol_json => $json, params => $param_bytes,
#       input_name => "data", input_shape => [1, 4]);
#   my @probs = $pred->predict(@values);

use strict;
use warnings;
use DynaLoader ();

our $VERSION = "0.1.0";
our @ISA = ("DynaLoader");

# the shared object is built by build.pl next to this tree
sub dl_load_flags { 0x01 }    # RTLD_GLOBAL for the embedded CPython

__PACKAGE__->bootstrap($VERSION);

package AI::MXNetTPU::Predictor;

use strict;
use warnings;
use Carp ();

sub new {
    my ($class, %args) = @_;
    for my $req (qw(symbol_json params input_shape)) {
        Carp::croak("missing required argument $req")
            unless defined $args{$req};
    }
    my $handle = AI::MXNetTPU::_create(
        $args{symbol_json}, $args{params},
        $args{dev_type} // 1, $args{dev_id} // 0,
        $args{input_name} // "data", $args{input_shape});
    return bless {
        handle     => $handle,
        input_name => $args{input_name} // "data",
    }, $class;
}

sub set_input {
    my ($self, @values) = @_;
    AI::MXNetTPU::_set_input($self->{handle}, $self->{input_name},
                             pack("f*", @values));
    return $self;
}

sub forward {
    my ($self) = @_;
    AI::MXNetTPU::_forward($self->{handle});
    return $self;
}

sub output_shape {
    my ($self, $index) = @_;
    return AI::MXNetTPU::_output_shape($self->{handle}, $index // 0);
}

sub output {
    my ($self, $index) = @_;
    $index //= 0;
    my $size = 1;
    $size *= $_ for $self->output_shape($index);
    return unpack("f*",
                  AI::MXNetTPU::_output($self->{handle}, $index, $size));
}

sub predict {
    my ($self, @values) = @_;
    return $self->set_input(@values)->forward->output(0);
}

sub DESTROY {
    my ($self) = @_;
    AI::MXNetTPU::_free($self->{handle}) if defined $self->{handle};
}

1;
