"""ResNet V1/V2 for the gluon model zoo.

Capability parity with the reference zoo
(python/mxnet/gluon/model_zoo/vision/resnet.py): depths 18/34/50/101/
152 in both the He2015 post-activation (v1) and the pre-activation (v2)
arrangements, same parameter names so published ``.params`` files load.

Implementation is table-driven rather than one class per variant: each
residual unit's conv stack is a row of ``_UNIT_TABLE`` keyed by
(version, kind) — kernel size, where the stride lands, padding, the
channel divisor, and whether the conv carries a bias (the reference's
v1 bottleneck keeps biases on its 1x1 convs; preserved here because the
parameter sets must match) — and a single ``_Unit``/``_ResNet`` pair
interprets the table.
"""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn
from ..model_store import get_model_file

__all__ = ["ResNetV1", "ResNetV2", "BasicBlockV1", "BasicBlockV2",
           "BottleneckV1", "BottleneckV2", "resnet18_v1", "resnet34_v1",
           "resnet50_v1", "resnet101_v1", "resnet152_v1", "resnet18_v2",
           "resnet34_v2", "resnet50_v2", "resnet101_v2", "resnet152_v2",
           "get_resnet"]


# Conv rows per residual unit: (kernel, takes_stride, padding,
# channel_divisor, with_bias). The unit's output channel count divided
# by ``channel_divisor`` gives the conv width; ``takes_stride`` marks
# where the unit's stride is applied (v1 strides its first conv, v2
# bottlenecks stride the middle 3x3 — the reference's arrangement).
_UNIT_TABLE = {
    (1, "basic"): ((3, True, 1, 1, False), (3, False, 1, 1, False)),
    (1, "bottleneck"): ((1, True, 0, 4, True), (3, False, 1, 4, False),
                        (1, False, 0, 1, True)),
    (2, "basic"): ((3, True, 1, 1, False), (3, False, 1, 1, False)),
    (2, "bottleneck"): ((1, False, 0, 4, False), (3, True, 1, 4, False),
                        (1, False, 0, 1, False)),
}


def _unit_conv(row, channels, stride, in_channels=0):
    kernel, takes_stride, pad, div, bias = row
    return nn.Conv2D(channels // div, kernel_size=kernel,
                     strides=stride if takes_stride else 1, padding=pad,
                     use_bias=bias, in_channels=in_channels)


class _Unit(HybridBlock):
    """One residual unit interpreting a ``_UNIT_TABLE`` row.

    v1 wraps conv/BN pairs in a ``body`` Sequential with the ReLU
    between pairs and adds the skip AFTER the last BN; v2 registers
    BN->ReLU->conv triples flat (pre-activation) and draws the skip
    from the first activation. Child registration order matches the
    reference blocks so auto-generated parameter names line up."""

    def __init__(self, version, kind, channels, stride, downsample=False,
                 in_channels=0, **kwargs):
        super(_Unit, self).__init__(**kwargs)
        self._version = version
        rows = _UNIT_TABLE[(version, kind)]
        if version == 1:
            self.body = nn.HybridSequential(prefix="")
            for i, row in enumerate(rows):
                ic = in_channels if i == 0 and row[0] == 3 else 0
                self.body.add(_unit_conv(row, channels, stride, ic))
                self.body.add(nn.BatchNorm())
                if i + 1 < len(rows):
                    self.body.add(nn.Activation("relu"))
            if downsample:
                self.downsample = nn.HybridSequential(prefix="")
                self.downsample.add(nn.Conv2D(
                    channels, kernel_size=1, strides=stride,
                    use_bias=False, in_channels=in_channels))
                self.downsample.add(nn.BatchNorm())
            else:
                self.downsample = None
        else:
            self._steps = []
            for i, row in enumerate(rows):
                bn = nn.BatchNorm()
                ic = in_channels if i == 0 and row[0] == 3 else 0
                conv = _unit_conv(row, channels, stride, ic)
                setattr(self, "bn%d" % (i + 1), bn)
                setattr(self, "conv%d" % (i + 1), conv)
                self._steps.append((bn, conv))
            if downsample:
                self.downsample = nn.Conv2D(
                    channels, 1, stride, use_bias=False,
                    in_channels=in_channels)
            else:
                self.downsample = None

    def hybrid_forward(self, F, x):
        if self._version == 1:
            shortcut = x if self.downsample is None else self.downsample(x)
            return F.Activation(self.body(x) + shortcut, act_type="relu")
        shortcut = x
        for i, (bn, conv) in enumerate(self._steps):
            x = F.Activation(bn(x), act_type="relu")
            if i == 0 and self.downsample is not None:
                shortcut = self.downsample(x)
            x = conv(x)
        return x + shortcut


def BasicBlockV1(channels, stride, downsample=False, in_channels=0,
                 **kwargs):
    """Reference parity: resnet.py BasicBlockV1 (resnet 18/34 v1)."""
    return _Unit(1, "basic", channels, stride, downsample, in_channels,
                 **kwargs)


def BottleneckV1(channels, stride, downsample=False, in_channels=0,
                 **kwargs):
    """Reference parity: resnet.py BottleneckV1 (resnet 50/101/152 v1)."""
    return _Unit(1, "bottleneck", channels, stride, downsample,
                 in_channels, **kwargs)


def BasicBlockV2(channels, stride, downsample=False, in_channels=0,
                 **kwargs):
    """Reference parity: resnet.py BasicBlockV2 (pre-activation)."""
    return _Unit(2, "basic", channels, stride, downsample, in_channels,
                 **kwargs)


def BottleneckV2(channels, stride, downsample=False, in_channels=0,
                 **kwargs):
    """Reference parity: resnet.py BottleneckV2."""
    return _Unit(2, "bottleneck", channels, stride, downsample,
                 in_channels, **kwargs)


class _ResNet(HybridBlock):
    """Stem + staged residual units + classifier, for either version.

    version 2 brackets the stages with the extra featureless BatchNorm
    up front and BN->ReLU after (the pre-activation arrangement needs
    its own final activation before pooling)."""

    version = None

    def __init__(self, kind, layers, channels, classes=1000,
                 thumbnail=False, **kwargs):
        super(_ResNet, self).__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        v = self.version
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            if v == 2:
                self.features.add(nn.BatchNorm(scale=False, center=False))
            if thumbnail:
                self.features.add(nn.Conv2D(channels[0], 3, 1, 1,
                                            use_bias=False, in_channels=0))
            else:
                self.features.add(nn.Conv2D(channels[0], 7, 2, 3,
                                            use_bias=False))
                self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(3, 2, 1))
            in_ch = channels[0]
            for i, n_units in enumerate(layers):
                stage = nn.HybridSequential(prefix="stage%d_" % (i + 1))
                out_ch = channels[i + 1]
                with stage.name_scope():
                    for j in range(n_units):
                        stage.add(_Unit(
                            v, kind, out_ch,
                            stride=(2 if i > 0 and j == 0 else 1),
                            downsample=(j == 0 and out_ch != in_ch),
                            in_channels=in_ch if j == 0 else out_ch,
                            prefix=""))
                self.features.add(stage)
                in_ch = out_ch
            if v == 2:
                self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation("relu"))
            self.features.add(nn.GlobalAvgPool2D())
            if v == 2:
                self.features.add(nn.Flatten())
            self.output = nn.Dense(classes, in_units=in_ch)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


class ResNetV1(_ResNet):
    """Reference parity: resnet.py ResNetV1 (accepts a block factory
    like the reference's class argument; the factory selects the
    _UNIT_TABLE row)."""

    version = 1

    def __init__(self, block, layers, channels, **kwargs):
        kind = "bottleneck" if block is BottleneckV1 else "basic"
        super(ResNetV1, self).__init__(kind, layers, channels, **kwargs)


class ResNetV2(_ResNet):
    """Reference parity: resnet.py ResNetV2."""

    version = 2

    def __init__(self, block, layers, channels, **kwargs):
        kind = "bottleneck" if block is BottleneckV2 else "basic"
        super(ResNetV2, self).__init__(kind, layers, channels, **kwargs)


resnet_spec = {
    18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}
resnet_net_versions = [ResNetV1, ResNetV2]
resnet_block_versions = [
    {"basic_block": BasicBlockV1, "bottle_neck": BottleneckV1},
    {"basic_block": BasicBlockV2, "bottle_neck": BottleneckV2},
]


def get_resnet(version, num_layers, pretrained=False, ctx=None,
               root="~/.mxnet/models", **kwargs):
    """Reference: resnet.py get_resnet."""
    assert num_layers in resnet_spec, \
        "Invalid resnet depth %d; options: %s" % (
            num_layers, sorted(resnet_spec))
    assert version in (1, 2)
    block_type, layers, channels = resnet_spec[num_layers]
    resnet_class = resnet_net_versions[version - 1]
    block_class = resnet_block_versions[version - 1][block_type]
    net = resnet_class(block_class, layers, channels, **kwargs)
    if pretrained:
        net.load_parameters(get_model_file(
            "resnet%d_v%d" % (num_layers, version), root=root), ctx=ctx)
    return net


def _variant(version, num_layers):
    def build(**kwargs):
        return get_resnet(version, num_layers, **kwargs)
    build.__name__ = "resnet%d_v%d" % (num_layers, version)
    build.__doc__ = "ResNet-%d v%d (reference: resnet.py %s)." % (
        num_layers, version, build.__name__)
    return build


resnet18_v1 = _variant(1, 18)
resnet34_v1 = _variant(1, 34)
resnet50_v1 = _variant(1, 50)
resnet101_v1 = _variant(1, 101)
resnet152_v1 = _variant(1, 152)
resnet18_v2 = _variant(2, 18)
resnet34_v2 = _variant(2, 34)
resnet50_v2 = _variant(2, 50)
resnet101_v2 = _variant(2, 101)
resnet152_v2 = _variant(2, 152)
