"""Custom python ops (operator.py) + runtime Pallas compile (rtc.py).

Reference patterns: tests/python/unittest/test_operator.py custom-op
cases (Sigmoid-style CustomOp with numeric grad check) and rtc usage.
"""
import jax
import jax.numpy as jnp
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, operator


@operator.register("scaled_square")
class ScaledSquareProp(operator.CustomOpProp):
    def __init__(self, scale=2.0):
        super().__init__(need_top_grad=True)
        self.scale = float(scale)

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        scale = self.scale

        class _Op(operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                self.assign(out_data[0], req[0],
                            in_data[0] * in_data[0] * scale)

            def backward(self, req, out_grad, in_data, out_data,
                         in_grad, aux):
                self.assign(in_grad[0], req[0],
                            out_grad[0] * 2.0 * scale * in_data[0])
        return _Op()


def test_custom_nd_forward():
    x = mx.nd.array(np.array([1.0, 2.0, 3.0], np.float32))
    y = mx.nd.Custom(x, op_type="scaled_square", scale=3.0)
    np.testing.assert_allclose(y.asnumpy(), [3, 12, 27])


def test_custom_nd_backward():
    x = mx.nd.array(np.array([1.0, 2.0, -1.5], np.float32))
    x.attach_grad()
    with autograd.record():
        y = mx.nd.Custom(x, op_type="scaled_square", scale=2.0)
        loss = y.sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 4.0 * x.asnumpy(),
                               rtol=1e-5)


def test_custom_jax_fn_in_jit():
    fn = operator.make_custom_jax_fn("scaled_square", scale=2.0)

    @jax.jit
    def f(x):
        return jnp.sum(fn(x))

    x = jnp.asarray([1.0, 2.0, 3.0], jnp.float32)
    assert abs(float(f(x)) - 28.0) < 1e-5
    g = jax.grad(lambda x: jnp.sum(fn(x)))(x)
    np.testing.assert_allclose(np.asarray(g), 4.0 * np.asarray(x),
                               rtol=1e-5)


def test_custom_symbol_graph():
    data = mx.sym.Variable("data")
    y = mx.sym.Custom(data, op_type="scaled_square", scale=2.0,
                      name="sq")
    exe = y.simple_bind(data=(3,))
    exe.arg_dict["data"][:] = mx.nd.array(
        np.array([1.0, 2.0, 3.0], np.float32))
    out = exe.forward()[0]
    np.testing.assert_allclose(out.asnumpy(), [2, 8, 18])


def test_rtc_pallas_module():
    src = """
def doubler(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0
"""
    mod = mx.rtc.PallasModule(src)
    k = mod.get_kernel("doubler")
    x = mx.nd.array(np.arange(8, dtype=np.float32).reshape(2, 4))
    out = k.launch([x])
    np.testing.assert_allclose(out.asnumpy(), x.asnumpy() * 2.0)


def test_rtc_kernel_cache_and_dtype():
    def addone(x_ref, o_ref):
        o_ref[...] = x_ref[...] + 1.0
    mod = mx.rtc.PallasModule(addone)
    k = mod.get_kernel("addone")
    a = mx.nd.array(np.zeros((4, 4), np.float32))
    r1 = k.launch([a])
    r2 = k.launch([a])
    assert len(k._cache) == 1
    np.testing.assert_allclose(r2.asnumpy(), np.ones((4, 4)))


def test_custom_symbol_kwarg_input():
    # reference form: sym.Custom(data=x, op_type=...) — keyword Symbol
    data = mx.sym.Variable("data")
    y = mx.sym.Custom(data=data, op_type="scaled_square", scale=3.0)
    exe = y.simple_bind(data=(2,))
    exe.arg_dict["data"][:] = mx.nd.array(np.array([1.0, 2.0], np.float32))
    out = exe.forward()[0]
    np.testing.assert_allclose(out.asnumpy(), [3, 12])


@operator.register("sigmoid_outdata")
class _SigmoidProp(operator.CustomOpProp):
    def create_operator(self, ctx, in_shapes, in_dtypes):
        class _Op(operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                x = in_data[0].asnumpy()
                self.assign(out_data[0], req[0],
                            mx.nd.array(1.0 / (1.0 + np.exp(-x))))

            def backward(self, req, out_grad, in_data, out_data,
                         in_grad, aux):
                # the canonical pattern: backward READS out_data
                y = out_data[0].asnumpy()
                g = out_grad[0].asnumpy()
                self.assign(in_grad[0], req[0], mx.nd.array(g * y * (1 - y)))
        return _Op()


def test_custom_backward_reads_out_data():
    x = mx.nd.array(np.array([0.5, -1.0, 2.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = mx.nd.Custom(x, op_type="sigmoid_outdata")
        y.sum().backward()
    s = 1.0 / (1.0 + np.exp(-x.asnumpy()))
    np.testing.assert_allclose(x.grad.asnumpy(), s * (1 - s), rtol=1e-5)
