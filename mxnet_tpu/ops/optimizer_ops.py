"""Optimizer update operators.

Reference: src/operator/optimizer_op.cc — in the reference, "the update IS
an operator" pushed through the engine; here each update is a pure fused
XLA kernel. Convention: ``num_outputs == len(mutate_inputs)`` and output i
is the new value of input ``mutate_inputs[i]`` — the NDArray layer writes
results back in place, preserving the reference's mutation semantics.

All updates apply ``rescale_grad``, optional gradient clipping and weight
decay exactly as the reference kernels do, so Python-side Optimizer classes
stay thin (reference: python/mxnet/optimizer.py).
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _prep_grad(grad, rescale_grad, clip_gradient):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g


@register("sgd_update", mutate_inputs=(0,),
          attr_defaults={"lr": 0.01, "wd": 0.0, "rescale_grad": 1.0,
                         "clip_gradient": -1.0, "lazy_update": True})
def _sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                clip_gradient=-1.0, **_ig):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    return weight - lr * (g + wd * weight)


@register("sgd_mom_update", mutate_inputs=(0, 2), num_outputs=2,
          attr_defaults={"lr": 0.01, "momentum": 0.0, "wd": 0.0,
                         "rescale_grad": 1.0, "clip_gradient": -1.0,
                         "lazy_update": True})
def _sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0, **_ig):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    mom_new = momentum * mom - lr * (g + wd * weight)
    return weight + mom_new, mom_new


@register("nag_mom_update", mutate_inputs=(0, 2), num_outputs=2,
          attr_defaults={"lr": 0.01, "momentum": 0.0, "wd": 0.0,
                         "rescale_grad": 1.0, "clip_gradient": -1.0})
def _nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0, **_ig):
    g = _prep_grad(grad, rescale_grad, clip_gradient) + wd * weight
    mom_new = momentum * mom + g
    return weight - lr * (g + momentum * mom_new), mom_new


@register("adam_update", mutate_inputs=(0, 2, 3), num_outputs=3,
          attr_defaults={"lr": 0.001, "beta1": 0.9, "beta2": 0.999,
                         "epsilon": 1e-8, "wd": 0.0, "rescale_grad": 1.0,
                         "clip_gradient": -1.0, "lazy_update": True})
def _adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                 **_ig):
    g = _prep_grad(grad, rescale_grad, clip_gradient) + wd * weight
    mean_new = beta1 * mean + (1 - beta1) * g
    var_new = beta2 * var + (1 - beta2) * jnp.square(g)
    return (weight - lr * mean_new / (jnp.sqrt(var_new) + epsilon),
            mean_new, var_new)


@register("rmsprop_update", mutate_inputs=(0, 2), num_outputs=2,
          attr_defaults={"lr": 0.001, "gamma1": 0.95, "epsilon": 1e-8,
                         "wd": 0.0, "rescale_grad": 1.0, "clip_gradient": -1.0,
                         "clip_weights": -1.0})
def _rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.95, epsilon=1e-8,
                    wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                    clip_weights=-1.0, **_ig):
    g = _prep_grad(grad, rescale_grad, clip_gradient) + wd * weight
    n_new = gamma1 * n + (1 - gamma1) * jnp.square(g)
    w = weight - lr * g / jnp.sqrt(n_new + epsilon)
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, n_new


@register("rmspropalex_update", mutate_inputs=(0, 2, 3, 4), num_outputs=4,
          attr_defaults={"lr": 0.001, "gamma1": 0.95, "gamma2": 0.9,
                         "epsilon": 1e-8, "wd": 0.0, "rescale_grad": 1.0,
                         "clip_gradient": -1.0, "clip_weights": -1.0})
def _rmspropalex_update(weight, grad, n, g_acc, delta, lr=0.001, gamma1=0.95,
                        gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                        clip_gradient=-1.0, clip_weights=-1.0, **_ig):
    g = _prep_grad(grad, rescale_grad, clip_gradient) + wd * weight
    n_new = gamma1 * n + (1 - gamma1) * jnp.square(g)
    g_acc_new = gamma1 * g_acc + (1 - gamma1) * g
    delta_new = gamma2 * delta - lr * g / jnp.sqrt(
        n_new - jnp.square(g_acc_new) + epsilon)
    w = weight + delta_new
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, n_new, g_acc_new, delta_new


@register("ftrl_update", mutate_inputs=(0, 2, 3), num_outputs=3,
          attr_defaults={"lr": 0.1, "lamda1": 0.01, "beta": 1.0, "wd": 0.0,
                         "rescale_grad": 1.0, "clip_gradient": -1.0})
def _ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                 rescale_grad=1.0, clip_gradient=-1.0, **_ig):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    n_new = n + jnp.square(g)
    sigma = (jnp.sqrt(n_new) - jnp.sqrt(n)) / lr
    z_new = z + g - sigma * weight
    w = jnp.where(
        jnp.abs(z_new) <= lamda1, jnp.zeros_like(weight),
        -(z_new - jnp.sign(z_new) * lamda1)
        / ((beta + jnp.sqrt(n_new)) / lr + wd))
    return w, z_new, n_new


@register("ftml_update", mutate_inputs=(0, 2, 3, 4), num_outputs=4,
          attr_defaults={"lr": 0.0025, "beta1": 0.6, "beta2": 0.999,
                         "epsilon": 1e-8, "t": 1, "wd": 0.0,
                         "rescale_grad": 1.0, "clip_grad": -1.0})
def _ftml_update(weight, grad, d, v, z, lr=0.0025, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, t=1, wd=0.0, rescale_grad=1.0, clip_grad=-1.0,
                 **_ig):
    g = _prep_grad(grad, rescale_grad, clip_grad) + wd * weight
    v_new = beta2 * v + (1 - beta2) * jnp.square(g)
    d_new = (1 - beta1 ** t) / lr * (
        jnp.sqrt(v_new / (1 - beta2 ** t)) + epsilon)
    sigma = d_new - beta1 * d
    z_new = beta1 * z + (1 - beta1) * g - sigma * weight
    return -z_new / d_new, d_new, v_new, z_new


@register("signsgd_update", mutate_inputs=(0,),
          attr_defaults={"lr": 0.01, "wd": 0.0, "rescale_grad": 1.0,
                         "clip_gradient": -1.0})
def _signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                    clip_gradient=-1.0, **_ig):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register("signum_update", mutate_inputs=(0, 2), num_outputs=2,
          attr_defaults={"lr": 0.01, "momentum": 0.0, "wd": 0.0,
                         "rescale_grad": 1.0, "clip_gradient": -1.0,
                         "wd_lh": 0.0})
def _signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0, **_ig):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    mom_new = momentum * mom - (1 - momentum) * g
    w = (1 - lr * wd_lh) * weight + lr * jnp.sign(mom_new) - lr * wd * weight
    return w, mom_new


@register("mp_sgd_update", mutate_inputs=(0, 2), num_outputs=2,
          attr_defaults={"lr": 0.01, "wd": 0.0, "rescale_grad": 1.0,
                         "clip_gradient": -1.0, "lazy_update": True})
def _mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, **_ig):
    """Mixed-precision SGD: fp32 master weights, low-precision working copy
    (reference: src/operator/optimizer_op.cc MP_SGD)."""
    g = _prep_grad(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    w32 = weight32 - lr * (g + wd * weight32)
    return w32.astype(weight.dtype), w32


@register("mp_sgd_mom_update", mutate_inputs=(0, 2, 3), num_outputs=3,
          attr_defaults={"lr": 0.01, "momentum": 0.0, "wd": 0.0,
                         "rescale_grad": 1.0, "clip_gradient": -1.0,
                         "lazy_update": True})
def _mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                       wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, **_ig):
    g = _prep_grad(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    mom_new = momentum * mom - lr * (g + wd * weight32)
    w32 = weight32 + mom_new
    return w32.astype(weight.dtype), mom_new, w32
