"""Operator library: importing this package populates the registry."""
from .registry import (OpDef, register, get_op, list_ops, invoke, invoke_raw,
                       alias)

from . import elemwise     # noqa: F401
from . import reduce       # noqa: F401
from . import matrix       # noqa: F401
from . import nn           # noqa: F401
from . import creation     # noqa: F401
from . import random_ops   # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import linalg       # noqa: F401
from . import control_flow  # noqa: F401
from . import image_ops    # noqa: F401
from . import contrib_ops  # noqa: F401
from . import detection_ops  # noqa: F401
from . import deformable_ops  # noqa: F401
from . import sampler_ops  # noqa: F401
from . import quantization_ops  # noqa: F401
from . import sparse_ops   # noqa: F401

__all__ = ["OpDef", "register", "get_op", "list_ops", "invoke", "invoke_raw",
           "alias"]
