"""Serving helper backing the native C predict ABI.

Reference: include/mxnet/c_predict_api.h + src/c_api/c_predict_api.cc
(MXPredCreate/SetInput/Forward/GetOutput on a symbol json + params
blob). The native layer (src/native/c_predict_api.cc) embeds CPython
and drives this module; keeping the marshalling here means the C side
is a thin, stable ABI while the compute path stays XLA.

Params blob format = mx.nd.save (zip of NPY entries, the framework's
checkpoint format); arg/aux entries use the reference's ``arg:name`` /
``aux:name`` prefixes (falling back to raw names).
"""
from __future__ import annotations

import os
import tempfile

import numpy as _np

from .base import MXNetError
from . import telemetry as _tm

__all__ = ["Predictor"]


class Predictor(object):
    """One bound inference executor (reference: c_predict_api.cc
    Predictor struct)."""

    def __init__(self, symbol_json, param_bytes, dev_type=1, dev_id=0,
                 input_shapes=None, input_types=None):
        from .symbol.symbol import load_json
        from .ndarray import utils as _utils
        from . import context as _ctx
        sym = load_json(symbol_json)
        fd, tmp = tempfile.mkstemp(suffix=".params")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(param_bytes)
            saved = _utils.load(tmp)
        finally:
            os.unlink(tmp)
        if not isinstance(saved, dict):
            raise MXNetError("param blob must be a named-tensor dict")
        arg_params, aux_params = {}, {}
        for k, v in saved.items():
            if k.startswith("arg:"):
                arg_params[k[4:]] = v
            elif k.startswith("aux:"):
                aux_params[k[4:]] = v
            else:
                arg_params[k] = v
        ctx = _ctx.tpu(dev_id) if dev_type == 2 else _ctx.cpu(dev_id)
        shapes = dict(input_shapes or {})
        # bind every buffer (args AND aux) in its checkpoint dtype
        # (fp16/bf16/int checkpoints must not silently widen to f4);
        # inputs default to f4 unless input_types overrides
        type_dict = {k: v.dtype for k, v in arg_params.items()}
        type_dict.update({k: v.dtype for k, v in aux_params.items()})
        type_dict.update({k: _np.dtype(t)
                          for k, t in (input_types or {}).items()})
        self._sym = sym
        self._arg_params = arg_params
        self._aux_params = aux_params
        self._ctx = ctx
        self._input_types = {k: _np.dtype(t)
                             for k, t in (input_types or {}).items()}
        self._exe = sym.simple_bind(ctx=ctx, grad_req="null",
                                    type_dict=type_dict, **shapes)
        for k, v in arg_params.items():
            if k in self._exe.arg_dict:
                self._exe.arg_dict[k][:] = v
        for k, v in aux_params.items():
            if k in self._exe.aux_dict:
                self._exe.aux_dict[k][:] = v
        self._input_names = list(shapes)
        self._outputs = None

    def set_input(self, key, data_bytes):
        """``data_bytes``: raw little-endian bytes in the bound array's
        dtype and shape (the C predict ABI hands over an opaque buffer;
        the bound dtype — f4 by default, or whatever ``input_types``
        declared — defines its layout, so fp16/bf16/int inputs
        round-trip without a silent f4 reinterpretation)."""
        if key not in self._exe.arg_dict:
            raise MXNetError("unknown input %r" % key)
        arr = self._exe.arg_dict[key]
        dt = _np.dtype(arr.dtype)
        want_bytes = int(_np.prod(arr.shape)) * dt.itemsize
        if len(data_bytes) != want_bytes:
            raise MXNetError(
                "input %r size mismatch: got %d bytes, want %d "
                "(shape %s, dtype %s)"
                % (key, len(data_bytes), want_bytes, tuple(arr.shape),
                   dt.name))
        try:
            wire_dt = dt.newbyteorder("<") if dt.itemsize > 1 else dt
        except (TypeError, ValueError):   # ml_dtypes (bf16) are LE-only
            wire_dt = dt
        flat = _np.frombuffer(data_bytes, dtype=wire_dt).astype(dt,
                                                                copy=False)
        from .ndarray.ndarray import array
        arr[:] = array(flat.reshape(arr.shape), dtype=dt)

    def forward(self):
        t0 = _tm.monotonic() if _tm._enabled else None
        self._outputs = self._exe.forward(is_train=False)
        if t0 is not None:
            _tm.counter("serving/requests_total",
                        "Inference requests accepted").inc()
            _tm.histogram("serving/request_seconds",
                          "Inference request latency (host-side, submit "
                          "to result)").observe(_tm.monotonic() - t0)

    def serve_metrics(self, port=0, addr="127.0.0.1"):
        """Start the telemetry ``/metrics`` + ``/healthz`` endpoint next
        to this predictor (inference deployments scrape it; see
        docs/observability.md). Returns the :class:`TelemetryServer`
        handle — keep a reference and ``close()`` it on shutdown."""
        from . import telemetry
        return telemetry.serve(port=port, addr=addr)

    def num_outputs(self):
        self._ensure_forward()
        return len(self._outputs)

    def get_output_shape(self, index):
        self._ensure_forward()
        return tuple(int(d) for d in self._outputs[index].shape)

    def get_output(self, index):
        """Returns raw float32 bytes of output ``index``."""
        self._ensure_forward()
        out = self._outputs[index].asnumpy().astype("<f4", copy=False)
        return out.tobytes()

    def _ensure_forward(self):
        if self._outputs is None:
            raise MXNetError("call forward() first")

    def reshape(self, input_shapes):
        """Rebind for new input shapes (reference: MXPredReshape). The
        graph program is shape-specialized by the jit cache; only the
        INPUT buffers are reallocated. Parameter and aux buffers whose
        shapes are input-independent are SHARED with this predictor
        (Executor.alias_args) — no host->device re-upload and no second
        copy of the weights in HBM, which is what makes a per-bucket
        executor ladder (serve.InferenceEngine) cost one weight set."""
        input_shapes = dict(input_shapes)
        new = Predictor.__new__(Predictor)
        new._sym = self._sym
        new._arg_params = self._arg_params
        new._aux_params = self._aux_params
        new._ctx = self._ctx
        new._input_types = getattr(self, "_input_types", {})
        type_dict = {k: v.dtype for k, v in self._arg_params.items()}
        type_dict.update({k: v.dtype for k, v in self._aux_params.items()})
        type_dict.update(new._input_types)
        new._exe = self._sym.simple_bind(ctx=self._ctx, grad_req="null",
                                         type_dict=type_dict,
                                         **input_shapes)
        # never alias an input buffer — not even one omitted from this
        # reshape call (a partial reshape infers the rest): set_input on
        # the new predictor must not overwrite the old one's feed
        no_share = set(input_shapes) | set(self._input_names)
        shared = [n for n in new._exe.arg_dict
                  if n not in no_share and n in self._exe.arg_dict
                  and new._exe.arg_dict[n].shape
                  == self._exe.arg_dict[n].shape]
        shared += [n for n in new._exe.aux_dict
                   if n in self._exe.aux_dict
                   and new._exe.aux_dict[n].shape
                   == self._exe.aux_dict[n].shape]
        new._exe.alias_args(self._exe, shared)
        # anything shape-coupled to the inputs (rare: e.g. a param whose
        # shape inference tracks the batch axis) still needs the copy
        resident = set(shared)
        for k, v in self._arg_params.items():
            if k in new._exe.arg_dict and k not in resident:
                new._exe.arg_dict[k][:] = v
        for k, v in self._aux_params.items():
            if k in new._exe.aux_dict and k not in resident:
                new._exe.aux_dict[k][:] = v
        # the input set is a property of the MODEL, not of this call:
        # keep any input name simple_bind inferred rather than narrowing
        # to the keys passed here
        new._input_names = list(input_shapes) + [
            n for n in self._input_names if n not in input_shapes]
        new._outputs = None
        return new
