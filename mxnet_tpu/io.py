"""Data iterators and the async input pipeline.

Reference: python/mxnet/io.py (DataDesc/DataBatch/DataIter at :60-180,
NDArrayIter :182, ResizeIter :578, PrefetchingIter :658, CSVIter via the
C++ registry src/io/iter_csv.cc) plus the C++ multi-worker decode path
src/io/iter_image_recordio_2.cc (num_parts/part_index sharding, OMP
parallel ParseChunk, PrefetcherParam double buffering).

TPU-native design: batches are prepared on host in NumPy (shuffle/slice/
pad are bandwidth-trivial) and shipped to device per batch.
``PrefetchingIter`` keeps the reference's one-deep thread double buffer;
``DataPipeline`` is the production path — a process pool decodes
batches in parallel (``MXNET_IO_WORKERS``), results reassemble in order
so the batch stream is bitwise-identical for any worker count, and a
k-deep staging buffer (``MXNET_IO_PREFETCH``) ``jax.device_put``s
upcoming batches so H2D overlaps the previous step's compute.

Sharding is a first-class iterator contract: ``num_parts`` /
``part_index`` produce disjoint, exhaustive partitions (every record in
exactly one part; tails land in the trailing parts), fixed at
construction exactly like the reference C++ loader. Per-epoch shuffles
permute WITHIN each part, drawn from a private RNG keyed by
``(seed, epoch)`` — deterministic on every host, never touching global
RNG state.
"""
from __future__ import annotations

import os
import queue as _queue
import random as _pyrandom
import threading
import time
from collections import OrderedDict, deque, namedtuple

import numpy as np

from .base import MXNetError
from .ndarray.ndarray import NDArray, array
from . import telemetry as _tm
from . import tracing as _tr

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter", "ImageRecordIter",
           "PrefetchingIter", "CSVIter", "LibSVMIter", "MNISTIter",
           "DataPipeline", "ArrayBatchSource", "RecordBatchSource",
           "shard_bounds", "mix_seed", "dist_parts"]


def shard_bounds(n, num_parts, part_index):
    """The half-open slice ``[lo, hi)`` of ``part_index`` when ``n``
    samples split into ``num_parts`` shards. The partition contract the
    whole input layer shares (reference: iter_image_recordio_2.cc
    num_parts/part_index chunk split): parts are DISJOINT and
    EXHAUSTIVE — every index lands in exactly one part — and sizes
    differ by at most one (``n % num_parts`` trailing parts get the
    extra sample)."""
    num_parts = int(num_parts)
    part_index = int(part_index)
    if num_parts < 1:
        raise MXNetError("num_parts must be >= 1, got %d" % num_parts)
    if not 0 <= part_index < num_parts:
        raise MXNetError("part_index %d out of range for num_parts %d"
                         % (part_index, num_parts))
    lo = n * part_index // num_parts
    hi = n * (part_index + 1) // num_parts
    return lo, hi


def dist_parts():
    """Per-host input-sharding arguments for multi-host training:
    ``(num_parts, part_index) = (process_count, process_index)`` once
    ``jax.distributed`` is live, ``(1, 0)`` single-process.  Pass them
    to any sharding iterator (NDArrayIter/CSVIter/ImageRecordIter/
    DataPipeline sources — the PR 6 contract) so rank r feeds shard r,
    which is exactly the slice the ``dist_tpu_sync`` global mesh maps
    onto rank r's devices::

        num_parts, part_index = mx.io.dist_parts()
        it = mx.io.NDArrayIter(X, y, batch_size=local_batch,
                               num_parts=num_parts, part_index=part_index)
        module.fit(it, kvstore="dist_tpu_sync", ...)

    Also publishes the ``io/host_shard_parts`` / ``io/host_shard_index``
    gauges so a scrape can confirm every host is feeding a distinct
    shard.

    Brings the ``jax.distributed`` runtime up itself when the
    environment describes a cluster — iterators are typically built
    BEFORE the kvstore, and a pre-runtime ``jax.process_count()`` of 1
    here would silently feed every rank the whole dataset.  The
    reference is held for the process lifetime (never released), so a
    later ``KVStore.close()`` cannot tear down the runtime out from
    under iterators still wired with these values.  Raises on a
    configured-but-broken cluster."""
    from . import dist_runtime as _dist
    _dist.acquire()
    parts, index = _dist.process_count(), _dist.process_index()
    if _tm._enabled:
        _tm.gauge("io/host_shard_parts",
                  "num_parts this host's input iterators shard over "
                  "(io.dist_parts: the process count)").set(parts)
        _tm.gauge("io/host_shard_index",
                  "part_index this host's input iterators feed "
                  "(io.dist_parts: the process index)").set(index)
    return parts, index


_MASK64 = (1 << 64) - 1


def mix_seed(*parts):
    """Deterministically mix integers into one 63-bit seed (splitmix64
    finalizer). Used to key per-epoch permutations and per-batch
    augmentation RNG: stable across processes and PYTHONHASHSEED, so a
    worker pool and the inline path draw identical streams."""
    h = 0x9E3779B97F4A7C15
    for p in parts:
        h = (h ^ (int(p) & _MASK64)) * 0xBF58476D1CE4E5B9 & _MASK64
        h = (h ^ (h >> 27)) * 0x94D049BB133111EB & _MASK64
        h ^= h >> 31
    return h & ((1 << 63) - 1)


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    """Data description: name, shape, plus dtype/layout
    (reference: python/mxnet/io.py DataDesc)."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return "DataDesc[%s,%s,%s,%s]" % (self.name, self.shape, self.dtype,
                                          self.layout)

    @staticmethod
    def get_batch_axis(layout):
        """Axis of the batch dimension in ``layout`` (0 if unspecified)."""
        if layout is None:
            return 0
        return layout.find("N")

    @staticmethod
    def get_list(shapes, types):
        if types is not None:
            type_dict = dict(types)
            return [DataDesc(x[0], x[1], type_dict[x[0]]) for x in shapes]
        return [DataDesc(x[0], x[1]) for x in shapes]


class DataBatch(object):
    """One mini-batch (reference: python/mxnet/io.py DataBatch)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            raise TypeError("data must be a list of NDArrays")
        if label is not None and not isinstance(label, (list, tuple)):
            raise TypeError("label must be a list of NDArrays")
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        data_shapes = [d.shape for d in self.data]
        if self.label:
            label_shapes = [l.shape for l in self.label]
        else:
            label_shapes = None
        return "{}: data shapes: {} label shapes: {}".format(
            self.__class__.__name__, data_shapes, label_shapes)


class DataIter(object):
    """Base iterator (reference: python/mxnet/io.py DataIter)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    """Normalize input data into an OrderedDict of name->numpy array
    (reference: python/mxnet/io.py _init_data)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = OrderedDict([(default_name, data[0])])
        else:
            data = OrderedDict(
                [("_%d_%s" % (i, default_name), d) for i, d in enumerate(data)])
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, a list of "
                        "them or dict with them as values")
    out = OrderedDict()
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        out[k] = np.asarray(v)
    return list(out.items())


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference: python/mxnet/io.py:182).

    Supports shuffle and the three ``last_batch_handle`` modes of the
    reference: ``pad`` (wrap the final short batch with leading samples,
    reporting ``pad``), ``discard``, and ``roll_over`` (carry the remainder
    to the next epoch).

    Beyond the reference: ``seed`` makes epoch shuffles deterministic —
    each epoch's permutation is drawn from a private RNG keyed by
    ``(seed, epoch)``, never from the global NumPy RNG, so user
    ``np.random.seed`` streams don't interleave with input shuffling and
    a resumed run replays the exact permutation of the interrupted one
    (:meth:`checkpoint_state` / :meth:`restore_state`).
    ``num_parts``/``part_index`` shard the arrays into disjoint,
    exhaustive partitions (see :func:`shard_bounds`).
    """

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label", seed=None, num_parts=1,
                 part_index=0):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)

        # pre-shard views, retained for elastic_reshard(): numpy slices
        # are views, so keeping these costs no extra memory
        self._full_data = list(self.data)
        self._full_label = list(self.label)
        self._elastic = None
        self._part_batch = None

        if num_parts > 1:
            lo, hi = shard_bounds(self.data[0][1].shape[0], num_parts,
                                  part_index)
            self.data = [(k, v[lo:hi]) for k, v in self.data]
            self.label = [(k, v[lo:hi]) for k, v in self.label]
        self.num_parts = int(num_parts)
        self.part_index = int(part_index)

        self.idx = np.arange(self.data[0][1].shape[0])
        self.shuffle = shuffle
        # epoch permutations come from a PRIVATE stream: unseeded
        # construction draws ONE anchor from the global RNG (so legacy
        # np.random.seed reproducibility holds) and everything after is
        # keyed by (anchor, epoch) — stateless per epoch, which is what
        # makes the cursor seekable
        if seed is None and shuffle:
            seed = int(np.random.randint(0, 2 ** 31 - 1))
        self._seed = seed
        self._epoch = -1
        self._base_data = self.data
        self._base_label = self.label
        self.last_batch_handle = last_batch_handle
        self.num_data = self.idx.shape[0]

        if last_batch_handle == "discard":
            new_n = self.num_data - self.num_data % batch_size
            self.num_data = new_n

        assert self.num_data >= batch_size, \
            "batch_size needs to be smaller than data size."
        self.cursor = -batch_size
        self._cache_data = None
        self._cache_label = None
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def hard_reset(self):
        """Ignore roll-over; restart from sample 0."""
        self._epoch += 1
        if self.shuffle:
            self._shuffle_data()
        self.cursor = -self.batch_size
        self._cache_data = None
        self._cache_label = None

    def reset(self):
        self._epoch += 1
        if self.shuffle:
            self._shuffle_data()
        # roll_over: keep the tail of the previous epoch at the front
        if (self.last_batch_handle == "roll_over"
                and 0 < self.cursor < self.num_data):
            self.cursor = -self.batch_size + (self.cursor - self.num_data)
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        if not self.iter_next():
            raise StopIteration
        data = self.getdata()
        label = self.getlabel()
        # roll_over: clear the carried-over cache only after BOTH data and
        # label consumed it
        if self.last_batch_handle == "roll_over" and self.cursor < 0:
            self._cache_data = None
            self._cache_label = None
        return DataBatch(data=data, label=label, pad=self.getpad(),
                         index=None)

    def _getdata(self, data_source, start=None, end=None):
        """Slice [start, end) from each source array as NDArray."""
        if start is None:
            start = 0
        if end is None:
            end = data_source[0][1].shape[0] if data_source else 0
        return [array(v[start:end]) for _, v in data_source]

    def _concat(self, first, second):
        return [array(np.concatenate((f.asnumpy(), s.asnumpy()), axis=0))
                for f, s in zip(first, second)]

    def _batchify(self, data_source):
        """Assemble the current batch, handling the final short batch per
        ``last_batch_handle``."""
        assert self.cursor < self.num_data, "DataIter needs reset."
        if (self.last_batch_handle == "roll_over" and self.cursor < 0):
            # remainder carried over from previous epoch
            assert (self._cache_data is not None
                    or self._cache_label is not None), \
                "next epoch should have cached data"
            cache = (self._cache_data if data_source is self.data
                     else self._cache_label)
            second = self._getdata(data_source, end=self.cursor
                                   + self.batch_size)
            return self._concat(cache, second)
        if self.cursor + self.batch_size <= self.num_data:
            return self._getdata(data_source, start=self.cursor,
                                 end=self.cursor + self.batch_size)
        # final short batch
        if self.last_batch_handle == "pad":
            first = self._getdata(data_source, start=self.cursor,
                                  end=self.num_data)
            pad = self.batch_size - (self.num_data - self.cursor)
            second = self._getdata(data_source, end=pad)
            return self._concat(first, second)
        # roll_over / discard: return the short tail (cached by next())
        return self._getdata(data_source, start=self.cursor,
                             end=self.num_data)

    def getdata(self):
        if (self.last_batch_handle == "roll_over"
                and self.num_data - self.batch_size < self.cursor < self.num_data):
            # cache the tail; caller sees StopIteration via iter_next bound
            self._cache_data = self._batchify(self.data)
            self._cache_label = self._batchify(self.label) if self.label else []
            raise StopIteration
        return self._batchify(self.data)

    def getlabel(self):
        if not self.label:
            return []
        if (self.last_batch_handle == "roll_over" and self.cursor < 0
                and self._cache_label is not None):
            cache, second = self._cache_label, self._getdata(
                self.label, end=self.cursor + self.batch_size)
            return self._concat(cache, second)
        return self._batchify(self.label)

    def getpad(self):
        if (self.last_batch_handle == "pad"
                and self.cursor + self.batch_size > self.num_data):
            return self.cursor + self.batch_size - self.num_data
        if (self.last_batch_handle == "roll_over"
                and -self.batch_size < self.cursor < 0):
            return -self.cursor
        return 0

    def getindex(self):
        return None

    def _shuffle_data(self):
        if self._elastic is not None:
            # elastic mode rebuilds the interleaved view per epoch (each
            # owned part carries its own (seed, epoch) permutation)
            self._elastic_view()
            return
        # permute the ORIGINAL arrays with the (seed, epoch)-keyed
        # stream: any epoch's view is reconstructible without replaying
        # the epochs before it (the seek in restore_state)
        perm = np.random.RandomState(
            mix_seed(self._seed, self._epoch) % (2 ** 32)).permutation(
            self._base_data[0][1].shape[0])
        self.data = [(k, v[perm]) for k, v in self._base_data]
        self.label = [(k, v[perm]) for k, v in self._base_label]

    # -- elastic reshard (checkpoint-free rescale, see module.fit) --------
    def elastic_reshard(self, base_world, owned_parts):
        """Re-view this iterator as the union of several BASE-world
        shards, microbatch-major — the input half of a checkpoint-free
        rescale (``kvstore='dist_tpu_sync'`` elastic mode).

        After the world shrinks from ``base_world`` ranks to ``W``
        survivors, survivor ``j`` owns base parts
        ``elastic.plan_microbatches(base_world, W, j)`` and each of its
        steps feeds ``A = base_world // W`` microbatches of the original
        per-rank batch ``L``.  This method rebuilds ``self.data`` so
        batch ``t`` is ``[A*L, ...]`` with rows ``[a*L:(a+1)*L)`` taken
        from base part ``owned_parts[a]``'s batch ``t`` — exactly the
        rows base rank ``owned_parts[a]`` would have fed, including that
        part's private ``(seed, epoch)`` shuffle permutation.  Stacked
        over survivors on the global mesh (``make_accum_batch_global``),
        microbatch ``a`` reproduces the pre-fault world's global batch
        rows bit-for-bit, which is what makes the post-rescale loss
        curve a bitwise continuation.

        Bitwise replay of a DEAD rank's shuffle stream requires every
        rank to have been constructed with the same explicit ``seed``
        (per-rank random anchors are irrecoverable).  ``roll_over``
        iterators cannot reshard (same reason they cannot seek).  Call
        :meth:`restore_state` afterwards to seek to the agreed step."""
        if self.last_batch_handle == "roll_over":
            raise MXNetError("NDArrayIter(last_batch_handle='roll_over') "
                             "cannot elastic_reshard: the carried tail "
                             "is not reconstructible")
        base_world = int(base_world)
        owned = tuple(int(p) for p in owned_parts)
        if not owned:
            raise MXNetError("elastic_reshard: empty owned_parts")
        for p in owned:
            if not 0 <= p < base_world:
                raise MXNetError("elastic_reshard: part %d out of range "
                                 "for base_world %d" % (p, base_world))
        if self._elastic is None:
            if self.num_parts > 1 and self.num_parts != base_world:
                raise MXNetError(
                    "elastic_reshard: iterator was sharded %d-way but "
                    "base_world is %d" % (self.num_parts, base_world))
            # the per-rank batch of the BASE world, fixed across any
            # number of reshards (grow back included)
            self._part_batch = int(self.batch_size)
        elif self._elastic[0] != base_world:
            raise MXNetError("elastic_reshard: base_world changed from "
                             "%d to %d" % (self._elastic[0], base_world))
        self._elastic = (base_world, owned)
        self.batch_size = len(owned) * self._part_batch
        self._elastic_view()
        self.num_data = self.data[0][1].shape[0]
        self.idx = np.arange(self.num_data)
        self.cursor = -self.batch_size
        self._cache_data = None
        self._cache_label = None

    def _elastic_view(self):
        """Build the microbatch-major interleaved arrays for the current
        epoch from the retained pre-shard views."""
        base_world, owned = self._elastic
        L = self._part_batch
        n_full = self._full_data[0][1].shape[0]
        bounds = [shard_bounds(n_full, base_world, p) for p in owned]
        perms = {}
        if self.shuffle:
            for lo, hi in bounds:
                if (hi - lo) not in perms:
                    perms[hi - lo] = np.random.RandomState(
                        mix_seed(self._seed, self._epoch)
                        % (2 ** 32)).permutation(hi - lo)
        nbs = set()
        for lo, hi in bounds:
            n = hi - lo
            nbs.add(n // L if self.last_batch_handle == "discard"
                    else -(-n // L))
        if len(nbs) != 1:
            raise MXNetError(
                "elastic_reshard: owned parts yield unequal batch "
                "counts %s (dataset size %d, base_world %d, per-part "
                "batch %d) — parts must be the same number of batches "
                "long" % (sorted(nbs), n_full, base_world, L))

        def build(source):
            out = []
            for k, v in source:
                secs = []
                for lo, hi in bounds:
                    part = v[lo:hi]
                    if self.shuffle:
                        part = part[perms[hi - lo]]
                    n = part.shape[0]
                    if self.last_batch_handle == "discard":
                        nb = n // L
                        part = part[:nb * L]
                    else:           # pad: wrap with the part's own head,
                        nb = -(-n // L)   # as the base rank itself would
                        if nb * L > n:
                            part = np.concatenate(
                                [part, part[:nb * L - n]], axis=0)
                    secs.append(part.reshape((nb, L) + part.shape[1:]))
                out.append((k, np.concatenate(secs, axis=1).reshape(
                    (-1,) + v.shape[1:])))
            return out

        self.data = build(self._full_data)
        self.label = build(self._full_label)

    def checkpoint_state(self, epoch=None, nbatch=None):
        """Resumable cursor for the checkpoint manifest: everything a
        fresh process needs to continue this stream at (epoch, batch)
        without replaying — the shuffle anchor plus the position.
        ``roll_over`` carries cross-epoch state that a seek cannot
        reconstruct, so it returns None (fit falls back to replay)."""
        if self.last_batch_handle == "roll_over":
            return None
        state = {"kind": "NDArrayIter",
                 "epoch": int(self._epoch if epoch is None else epoch),
                 "batch": int(nbatch or 0),
                 "seed": self._seed,
                 "shuffle": bool(self.shuffle),
                 "batch_size": int(self.batch_size),
                 "num_data": int(self.num_data),
                 "num_parts": self.num_parts,
                 "part_index": self.part_index}
        if self._elastic is not None:
            state["elastic"] = {"base_world": self._elastic[0],
                                "owned": list(self._elastic[1]),
                                "part_batch": int(self._part_batch)}
        return state

    def restore_state(self, cursor):
        """Seek to a :meth:`checkpoint_state` position: applies that
        epoch's permutation and points the cursor at batch ``batch`` —
        no batches are drawn or decoded on the way. The seed is ADOPTED
        (it is part of the position); every other field identifies the
        stream and must match, so a cursor from a differently-configured
        iterator raises (fit then falls back to replay) instead of
        silently seeking to the wrong samples."""
        if self.last_batch_handle == "roll_over":
            raise MXNetError("NDArrayIter(last_batch_handle='roll_over') "
                             "cannot seek: the carried tail is not in "
                             "the cursor")
        if cursor.get("kind") not in (None, "NDArrayIter"):
            raise MXNetError("io cursor kind %r is not an NDArrayIter "
                             "cursor" % cursor.get("kind"))
        el = cursor.get("elastic")
        if el and self._elastic is None:
            # a cursor taken post-rescale seeks on a fresh (relaunched)
            # iterator by first re-applying the reshard
            self.elastic_reshard(el["base_world"], el["owned"])
        mine = {"shuffle": bool(self.shuffle),
                "batch_size": int(self.batch_size),
                "num_data": int(self.num_data),
                "num_parts": int(self.num_parts),
                "part_index": int(self.part_index)}
        for key, val in mine.items():
            if cursor.get(key) is not None and cursor[key] != val:
                raise MXNetError(
                    "io cursor was taken over a stream with %s=%r but "
                    "this iterator has %r — not the same stream"
                    % (key, cursor[key], val))
        if cursor.get("seed") is not None:
            self._seed = cursor["seed"]
        self._epoch = int(cursor["epoch"])
        if self.shuffle:
            self._shuffle_data()
        self.cursor = int(cursor.get("batch", 0)) * self.batch_size \
            - self.batch_size
        self._cache_data = None
        self._cache_label = None


class ResizeIter(DataIter):
    """Resize another iterator to ``size`` batches per epoch
    (reference: python/mxnet/io.py:578)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            try:
                self.current_batch = self.data_iter.next()
            except StopIteration:
                # an iterator that is empty even after reset() can never
                # fill `size` batches — a clear error beats the bare
                # StopIteration escaping mid-epoch
                raise MXNetError(
                    "ResizeIter: wrapped %s yielded no batches after "
                    "reset(); cannot resize an empty iterator to %d "
                    "batches" % (type(self.data_iter).__name__, self.size))
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Thread-based read-ahead over one or more iterators
    (reference: python/mxnet/io.py:658 — same double-buffer design; the
    reference uses it to overlap C++ decode with training; here it overlaps
    host batch prep with device compute).

    ``device_prefetch=True`` additionally stages each prefetched batch
    onto the accelerator from INSIDE the worker thread, so the
    host→device copy overlaps the previous step's compute — the TPU
    analog of the reference's pinned-host staging buffers
    (src/storage/ pinned memory + iter prefetcher)."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 device_prefetch=False, ctx=None):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.n_iter = len(iters)
        assert self.n_iter > 0
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self._device_prefetch = device_prefetch
        self._stage_ctx = ctx
        self.batch_size = self.provide_data[0][1][0]
        self.data_ready = [threading.Event() for _ in range(self.n_iter)]
        self.data_taken = [threading.Event() for _ in range(self.n_iter)]
        for e in self.data_taken:
            e.set()
        self.started = False
        self.current_batch = [None for _ in range(self.n_iter)]
        self.next_batch = [None for _ in range(self.n_iter)]
        self._tm_epoch_t0 = None
        self._tm_epoch_samples = 0
        self.prefetch_threads = []
        self._start_threads()

    def _start_threads(self):
        """(Re)spawn the per-iterator prefetch threads. Event state is
        preserved across a close(): a batch fetched before close stays
        in ``next_batch`` with its ready flag set, so a restarted
        consumer continues exactly where it stopped."""
        if self.started:
            return
        # a close() whose join timed out can leave a worker finishing
        # its fetch; wait it out — two workers interleaving next() on
        # one underlying iterator would corrupt the stream
        for t in self.prefetch_threads:
            t.join()
        # restore the parked-batch invariant: close() wakes waiting
        # workers by force-setting data_taken, and an exiting worker
        # consumes nothing. A parked batch (ready set) must keep
        # data_taken clear, or the fresh worker would pass its wait()
        # immediately and overwrite the batch before the consumer
        # reads it.
        for ready, taken in zip(self.data_ready, self.data_taken):
            if ready.is_set():
                taken.clear()
        self.started = True

        def prefetch_func(self, i):
            while True:
                self.data_taken[i].wait()
                if not self.started:
                    break
                try:
                    batch = self.iters[i].next()
                    if self._device_prefetch and batch is not None:
                        batch = self._stage(batch)
                    self.next_batch[i] = batch
                except StopIteration:
                    self.next_batch[i] = None
                self.data_taken[i].clear()
                self.data_ready[i].set()
                if not self.started:
                    # a close() that arrived mid-fetch had its wake-up
                    # signal erased by the clear() above — exit here
                    # instead of blocking in wait() past the join
                    break

        self.prefetch_threads = [
            threading.Thread(target=prefetch_func, args=[self, i], daemon=True)
            for i in range(self.n_iter)]
        for t in self.prefetch_threads:
            t.start()

    def _stage(self, batch):
        """device_put every array of the batch from the worker thread
        (async H2D; compute on the main thread proceeds meanwhile)."""
        import jax
        from .context import current_context
        ctx = self._stage_ctx or current_context()
        dev = ctx.jax_device() if hasattr(ctx, "jax_device") else ctx

        def put(arrs):
            out = []
            for a in arrs or []:
                if isinstance(a, NDArray):
                    a._set_data(jax.device_put(a._data, dev))
                out.append(a)
            return out

        batch.data = put(batch.data)
        batch.label = put(batch.label)
        return batch

    def close(self):
        """Stop the prefetch threads deterministically (the reference
        relied on ``__del__`` firing — on TPU VMs a leaked decode
        thread keeps the process alive past SIGTERM). Idempotent, and
        NOT terminal: ``reset()`` or the next ``iter_next()`` respawns
        the workers, so a closed iterator handed to a second ``fit``
        just works."""
        if not self.started:
            return
        self.started = False
        for e in self.data_taken:
            e.set()
        for t in self.prefetch_threads:
            t.join(timeout=5.0)
        # handles stay: _start_threads joins any straggler that was
        # still mid-fetch when the timed join gave up, then repairs the
        # event state, before spawning replacements

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(x, DataDesc) else DataDesc(*x)
                     for x in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(x, DataDesc) else DataDesc(*x)
                     for x in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    def reset(self):
        if self.started:
            for e in self.data_ready:
                e.wait()
        for i in self.iters:
            i.reset()
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()
        self._start_threads()
        if _tm._enabled:
            # epoch throughput: samples served since the previous reset
            now = _tm.monotonic()
            if self._tm_epoch_t0 is not None and self._tm_epoch_samples:
                dt = now - self._tm_epoch_t0
                if dt > 0:
                    _tm.gauge("io/epoch_samples_per_sec",
                              "Input-pipeline throughput over the last "
                              "epoch").set(self._tm_epoch_samples / dt)
            self._tm_epoch_t0 = now
            self._tm_epoch_samples = 0

    def iter_next(self):
        self._start_threads()       # no-op unless close()d
        t0 = None
        if _tm._enabled:
            # ready events double as the prefetch queue: depth = batches
            # staged ahead of the consumer right now
            _tm.gauge("io/queue_depth", "Prefetched batches ready ahead "
                      "of the consumer").set(
                sum(1 for e in self.data_ready if e.is_set()))
            t0 = _tm.monotonic()
        # the trace hook rides independently of the telemetry gate: the
        # step timeline must keep its input-stall span even with
        # MXNET_TELEMETRY=0
        tctx = _tr.active()
        if tctx is not None and t0 is None:
            t0 = _tm.monotonic()
        for e in self.data_ready:
            e.wait()
        if t0 is not None:
            t1 = _tm.monotonic()
            if _tm._enabled:
                _tm.histogram("io/batch_wait_seconds",
                              "Time the consumer blocked waiting for the "
                              "prefetcher").observe(
                    t1 - t0, trace_id=tctx.trace_id if tctx else None)
            if tctx is not None:
                # inside a train.step timeline this is the input-stall
                # share of the step's data-wait
                _tr.record_span("io.batch_wait", tctx, t0, t1)
        if self.next_batch[0] is None:
            # all sub-iterators end together
            assert all(b is None for b in self.next_batch), \
                "Number of entry mismatches between iterators"
            return False
        assert all(b is not None for b in self.next_batch), \
            "Number of entry mismatches between iterators"
        self.current_batch = DataBatch(
            sum([b.data for b in self.next_batch], []),
            sum([(b.label or []) for b in self.next_batch], []),
            self.next_batch[0].pad,
            self.next_batch[0].index,
            provide_data=self.provide_data,
            provide_label=self.provide_label)
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()
        if _tm._enabled:
            _tm.counter("io/batches_total",
                        "Batches served by prefetching iterators").inc()
            n = self.batch_size or 0
            if n:
                _tm.counter("io/samples_total", "Samples served by "
                            "prefetching iterators").inc(n)
                if self._tm_epoch_t0 is None:
                    self._tm_epoch_t0 = _tm.monotonic()
                self._tm_epoch_samples += n
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class CSVIter(DataIter):
    """Iterate over CSV files (reference: src/io/iter_csv.cc; the C++
    iterator streams chunks — here the file is memory-mapped once via
    numpy, which covers the same scale for host-side CSVs).

    ``num_parts``/``part_index`` shard the rows under the shared
    partition contract (:func:`shard_bounds`), composing with per-host
    data parallelism like the RecordIO iterators."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, dtype="float32",
                 shuffle=False, seed=None, num_parts=1, part_index=0, **_kw):
        data = np.loadtxt(data_csv, delimiter=",", dtype=dtype, ndmin=2)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=dtype, ndmin=2)
            label = label.reshape((-1,) + tuple(label_shape))
        self._inner = NDArrayIter(
            data, label, batch_size=batch_size,
            last_batch_handle="pad" if round_batch else "discard",
            label_name="label", shuffle=shuffle, seed=seed,
            num_parts=num_parts, part_index=part_index)
        super().__init__(batch_size)

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def checkpoint_state(self, epoch=None, nbatch=None):
        return self._inner.checkpoint_state(epoch, nbatch)

    def restore_state(self, cursor):
        self._inner.restore_state(cursor)


class LibSVMIter(DataIter):
    """Iterate over LibSVM-format text files producing CSR data batches
    (reference: src/io/iter_libsvm.cc — ``label idx:val idx:val ...``
    per line, optional separate label file with multi-output rows).

    Batches carry ``CSRNDArray`` data so downstream ``sparse.dot``
    computes on the nonzeros only; labels are dense. The whole file is
    parsed host-side once (the sparse training sets the reference
    targets — kddb, criteo — are host-RAM scale).
    """

    def __init__(self, data_libsvm, data_shape, label_libsvm=None,
                 label_shape=(1,), batch_size=1, round_batch=True,
                 dtype="float32", **_kw):
        from .ndarray import sparse as _sp
        self._num_features = int(np.prod(data_shape))
        vals, cols, indptr, labels = self._parse(data_libsvm, dtype)
        if label_libsvm is not None:
            lv, lc, lp, _ = self._parse(label_libsvm, dtype)
            width = int(np.prod(label_shape))
            lab = np.zeros((len(lp) - 1, width), dtype=dtype)
            rows = np.repeat(np.arange(len(lp) - 1), np.diff(lp))
            lab[rows, lc] = lv
            labels = lab
        else:
            labels = labels.reshape(-1, 1)
        self._vals, self._cols, self._indptr = vals, cols, indptr
        self._labels = labels
        self._n = len(indptr) - 1
        self._round = round_batch
        self._cursor = 0
        self._sp = _sp
        self._dtype = dtype
        super().__init__(batch_size)
        self.provide_data = [DataDesc("data",
                                      (batch_size, self._num_features))]
        self.provide_label = [DataDesc("softmax_label",
                                       (batch_size,) + tuple(label_shape))]

    def _parse(self, path, dtype):
        vals, cols, counts, labels = [], [], [], []
        with open(path) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                n = 0
                for tok in parts[1:]:
                    i, _, v = tok.partition(":")
                    cols.append(int(i))
                    vals.append(float(v))
                    n += 1
                counts.append(n)
        indptr = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        return (np.asarray(vals, dtype=dtype),
                np.asarray(cols, dtype=np.int64), indptr,
                np.asarray(labels, dtype=dtype))

    def reset(self):
        self._cursor = 0

    def iter_next(self):
        return self._cursor < self._n

    def next(self):
        if not self.iter_next():
            raise StopIteration
        lo = self._cursor
        hi = min(lo + self.batch_size, self._n)
        pad = self.batch_size - (hi - lo)
        if pad and not self._round:
            # round_batch=False discards the incomplete tail batch
            self._cursor = self._n
            raise StopIteration
        take = list(range(lo, hi)) + [i % self._n for i in range(pad)]
        ptr = np.zeros(len(take) + 1, dtype=np.int64)
        vs, cs = [], []
        for j, r in enumerate(take):
            s, e = self._indptr[r], self._indptr[r + 1]
            vs.append(self._vals[s:e])
            cs.append(self._cols[s:e])
            ptr[j + 1] = ptr[j] + (e - s)
        data = self._sp.CSRNDArray(
            np.concatenate(vs) if vs else np.zeros(0, self._dtype),
            np.concatenate(cs) if cs else np.zeros(0, np.int64), ptr,
            (len(take), self._num_features))
        label = array(self._labels[[t for t in take]])
        self._cursor = hi
        return DataBatch(data=[data], label=[label], pad=pad)


class MNISTIter(DataIter):
    """Iterate over the MNIST idx-format files (reference:
    src/io/iter_mnist.cc:260 — same ubyte/idx decode, host-side)."""

    def __init__(self, image, label, batch_size=128, shuffle=True, flat=False,
                 seed=0, **_kw):
        import gzip
        import struct

        def _open(p):
            return gzip.open(p, "rb") if str(p).endswith(".gz") else open(p, "rb")

        with _open(image) as f:
            magic, num, rows, cols = struct.unpack(">IIII", f.read(16))
            if magic != 2051:
                raise MXNetError("bad MNIST image magic %d" % magic)
            img = np.frombuffer(f.read(), dtype=np.uint8).reshape(
                num, rows, cols)
        with _open(label) as f:
            magic, num_l = struct.unpack(">II", f.read(8))
            if magic != 2049:
                raise MXNetError("bad MNIST label magic %d" % magic)
            lab = np.frombuffer(f.read(), dtype=np.uint8)
        img = img.astype(np.float32) / 255.0
        if flat:
            img = img.reshape(num, rows * cols)
        else:
            img = img.reshape(num, 1, rows, cols)
        if shuffle:
            rng = np.random.RandomState(seed)
            perm = rng.permutation(num)
            img, lab = img[perm], lab[perm]
        self._inner = NDArrayIter(img, lab.astype(np.float32),
                                  batch_size=batch_size,
                                  last_batch_handle="discard")
        super().__init__(batch_size)

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


def ImageRecordIter(path_imgrec=None, data_shape=None, batch_size=1,
                    shuffle=False, rand_crop=False, rand_mirror=False,
                    mean_r=0, mean_g=0, mean_b=0, std_r=1, std_g=1, std_b=1,
                    **kwargs):
    """RecordIO image iterator (reference: the C++-registered
    ImageRecordIter, src/io/iter_image_recordio_2.cc:735). Thin factory
    over image.ImageIter with the same flat-kwargs CLI surface."""
    from .image import ImageIter
    import numpy as _np
    mean = None
    std = None
    if mean_r or mean_g or mean_b:
        mean = _np.array([mean_r, mean_g, mean_b])
    if (std_r, std_g, std_b) != (1, 1, 1):
        std = _np.array([std_r, std_g, std_b])
    prefetch = kwargs.pop("prefetch_buffer", None)
    it = ImageIter(batch_size=batch_size, data_shape=data_shape,
                   path_imgrec=path_imgrec, shuffle=shuffle,
                   rand_crop=rand_crop, rand_mirror=rand_mirror,
                   mean=mean, std=std, **kwargs)
    if prefetch:
        # reference parity: ImageRecordIter is prefetched by default in
        # C++ (PrefetcherParam); here opt-in so the single-threaded CI
        # host isn't forced to pay the double-buffer thread
        it = PrefetchingIter(it)
    return it


# ---------------------------------------------------------------------------
# async multi-worker input pipeline (reference: the C++ prefetcher +
# OMP decode pool of src/io/iter_image_recordio_2.cc, rebuilt as a
# process pool feeding a k-deep device staging buffer)
# ---------------------------------------------------------------------------

def _pipeline_mp_context():
    """Multiprocessing context for pipeline workers. Shares the
    ``MXNET_DATALOADER_START_METHOD`` knob with the gluon DataLoader:
    fork shares the source copy-on-write; spawn/forkserver pickle it
    (every shipped source keeps ``__getstate__`` handle-free)."""
    import multiprocessing
    from . import config as _config
    method = _config.get("MXNET_DATALOADER_START_METHOD")
    valid = multiprocessing.get_all_start_methods()
    if method not in valid:
        if "MXNET_DATALOADER_START_METHOD" in os.environ:
            raise MXNetError(
                "MXNET_DATALOADER_START_METHOD=%r is not a start method "
                "on this platform (valid: %s)" % (method, ", ".join(valid)))
        method = valid[0]
    return multiprocessing.get_context(method)


def _pipeline_worker_loop(source, in_q, out_q, shm_prefix):
    """Pipeline worker body (module-level so both fork and spawn can
    target it): pull ``(epoch, index)`` tasks, materialize the batch
    via ``source.get_batch`` — a pure function of (epoch, index), so
    ANY worker produces identical bytes — and ship the arrays through
    POSIX shared memory. Segment names are deterministic
    (``prefix-epoch-index-leaf``) so the parent can reclaim what a
    CRASHED worker staged but never reported. The ``io.worker`` fault
    point fires before each decode; a ``crash`` armed there is how
    tests prove the restart path."""
    from multiprocessing import shared_memory, resource_tracker
    from . import fault as _fault
    try:
        # one decode lane per worker: cv2's internal thread pool times
        # N worker processes is a thread storm that scales at ~1x —
        # process-level parallelism is the scaling axis here
        import cv2
        cv2.setNumThreads(0)
    except Exception:
        pass
    while True:
        task = in_q.get()
        if task is None:
            break
        epoch, index = task
        metas = []
        try:
            _fault.inject("io.worker")
            t0 = time.monotonic()
            data, label, pad = source.get_batch(epoch, index)
            dt = time.monotonic() - t0
            n_data = len(data)
            for li, arr in enumerate(list(data) + list(label)):
                arr = np.ascontiguousarray(arr)
                name = "%s-%d-%d-%d" % (shm_prefix, epoch, index, li)
                try:
                    shm = shared_memory.SharedMemory(
                        name=name, create=True, size=max(1, arr.nbytes))
                except FileExistsError:
                    # stale segment from a crashed attempt at this very
                    # batch (the pool never decodes one task twice
                    # concurrently, so this is safe to reclaim)
                    try:
                        old = shared_memory.SharedMemory(name=name)
                        old.close()
                        old.unlink()
                    except FileNotFoundError:
                        pass
                    shm = shared_memory.SharedMemory(
                        name=name, create=True, size=max(1, arr.nbytes))
                np.ndarray(arr.shape, arr.dtype, buffer=shm.buf)[...] = arr
                metas.append((shm.name, arr.shape, str(arr.dtype)))
                # the CONSUMER unlinks; unregister so this process's
                # resource tracker doesn't double-free at exit
                try:
                    resource_tracker.unregister(shm._name, "shared_memory")
                except Exception:
                    pass
                shm.close()
            out_q.put((epoch, index, (metas, n_data, pad, dt), None))
        except Exception as e:
            # segments staged before the failure would otherwise leak
            # in /dev/shm — exactly when memory is already tight
            for name, _shape, _dtype in metas:
                try:
                    seg = shared_memory.SharedMemory(name=name)
                    seg.close()
                    seg.unlink()
                except Exception:
                    pass
            out_q.put((epoch, index, None, repr(e)))


def _shm_load(payload):
    """Map a worker's shm segments back into numpy (copy, then unlink:
    the consumer is the only party that frees transport memory)."""
    from multiprocessing import shared_memory
    metas, n_data, pad, dt = payload
    arrs = []
    for name, shape, dtype in metas:
        shm = shared_memory.SharedMemory(name=name)
        arrs.append(np.ndarray(shape, np.dtype(dtype),
                               buffer=shm.buf).copy())
        shm.close()
        try:
            shm.unlink()
        except FileNotFoundError:
            pass
    return arrs[:n_data], arrs[n_data:], pad, dt


def _shm_unlink(payload):
    """Release the segments of a batch that will never be consumed."""
    if not payload:
        return
    from multiprocessing import shared_memory
    for name, _shape, _dtype in payload[0]:
        try:
            shm = shared_memory.SharedMemory(name=name)
            shm.close()
            shm.unlink()
        except Exception:
            pass


class _BatchSourceBase(object):
    """Scaffolding every pipeline source shares — the shard/validate
    step, seeded per-epoch permutations, and the cursor fingerprint —
    kept in ONE place so the sources can't drift apart on the
    determinism contract. Subclasses call :meth:`_init_source` from
    ``__init__`` and use the returned ``(lo, hi)`` to take their
    shard's slice."""

    def _init_source(self, total, batch_size, shuffle, seed,
                     last_batch_handle, num_parts, part_index):
        self.batch_size = int(batch_size)
        lo, hi = shard_bounds(total, num_parts, part_index)
        self.num_data = int(hi - lo)
        if last_batch_handle not in ("pad", "discard"):
            raise MXNetError(
                "%s supports last_batch_handle 'pad' or 'discard', got %r"
                % (type(self).__name__, last_batch_handle))
        if self.num_data < self.batch_size:
            raise MXNetError(
                "batch_size %d exceeds shard size %d (part %d/%d)"
                % (self.batch_size, self.num_data, part_index, num_parts))
        self.last_batch_handle = last_batch_handle
        self.shuffle = bool(shuffle)
        self.seed = 0 if seed is None else int(seed)
        self.num_parts = int(num_parts)
        self.part_index = int(part_index)
        self._perm_cache = (None, None)
        return lo, hi

    def set_seed(self, seed):
        self.seed = int(seed)
        self._perm_cache = (None, None)

    def num_batches(self, epoch=0):
        if self.last_batch_handle == "discard":
            return self.num_data // self.batch_size
        return -(-self.num_data // self.batch_size)

    def _perm(self, epoch):
        if self._perm_cache[0] != epoch:
            perm = np.random.RandomState(
                mix_seed(self.seed, epoch) % (2 ** 32)).permutation(
                self.num_data)
            self._perm_cache = (epoch, perm)
        return self._perm_cache[1]

    def cursor_fingerprint(self):
        """Identity of this stream for the resumable cursor: restore
        refuses to seek a cursor taken over a different stream."""
        return {"source": type(self).__name__, "seed": self.seed,
                "shuffle": self.shuffle, "num_data": self.num_data,
                "batch_size": self.batch_size,
                "num_parts": self.num_parts,
                "part_index": self.part_index}


class ArrayBatchSource(_BatchSourceBase):
    """Picklable batch source over in-memory arrays for
    :class:`DataPipeline`.

    The pipeline source contract: ``get_batch(epoch, index)`` is a PURE
    function of its arguments plus construction parameters — what makes
    the multi-worker stream bitwise-identical to the inline one and the
    shard cursor seekable in O(1). Epoch shuffles draw from
    ``mix_seed(seed, epoch)`` (never global RNG state);
    ``num_parts``/``part_index`` shard per :func:`shard_bounds`;
    ``augment_fn(data_list, rng) -> data_list`` (a picklable,
    module-level function) runs with an RNG keyed by
    ``(seed, epoch, index)``.
    """

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 seed=0, last_batch_handle="pad", num_parts=1, part_index=0,
                 data_name="data", label_name="softmax_label",
                 augment_fn=None):
        data = _init_data(data, allow_empty=False, default_name=data_name)
        label = _init_data(label, allow_empty=True, default_name=label_name)
        lo, hi = self._init_source(data[0][1].shape[0], batch_size,
                                   shuffle, seed, last_batch_handle,
                                   num_parts, part_index)
        self._data = [(k, v[lo:hi]) for k, v in data]
        self._label = [(k, v[lo:hi]) for k, v in label]
        self.augment_fn = augment_fn

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self._data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self._label]

    def _take(self, epoch, index):
        n = self.num_batches(epoch)
        if not 0 <= index < n:
            raise MXNetError("batch index %d out of range [0, %d)"
                             % (index, n))
        lo = index * self.batch_size
        hi = min(lo + self.batch_size, self.num_data)
        pad = self.batch_size - (hi - lo)
        idx = np.arange(lo, hi)
        if pad:
            # wrap the short tail with leading samples (NDArrayIter
            # 'pad' semantics; 'discard' never reaches here)
            idx = np.concatenate([idx, np.arange(pad)])
        if self.shuffle:
            idx = self._perm(epoch)[idx]
        return idx, pad

    def get_batch(self, epoch, index):
        idx, pad = self._take(epoch, index)
        data = [v[idx] for _k, v in self._data]
        label = [v[idx] for _k, v in self._label]
        if self.augment_fn is not None:
            rng = np.random.RandomState(
                mix_seed(self.seed, epoch, index, 0xA4) % (2 ** 32))
            data = self.augment_fn(data, rng)
        return data, label, pad


class RecordBatchSource(_BatchSourceBase):
    """Picklable sharded RecordIO image source for :class:`DataPipeline`:
    packed ``(IRHeader, jpeg)`` records from an INDEXED ``.rec`` are
    decoded + augmented on whichever worker draws the batch.

    Only paths cross the pickle boundary (the ``MXRecordIO.__getstate__``
    contract); the reader and augmenter list open lazily per process.
    Augmentation RNG (stdlib + numpy global, which the image augmenters
    draw from) is seeded per batch by ``mix_seed(seed, epoch, index)``
    and restored afterwards, so crops/flips are bitwise-identical for
    any worker count and never perturb the caller's RNG streams.
    """

    def __init__(self, path_imgrec, data_shape, batch_size,
                 path_imgidx=None, label_width=1, shuffle=False, seed=0,
                 num_parts=1, part_index=0, last_batch_handle="pad",
                 aug_kwargs=None):
        self.path_imgrec = path_imgrec
        self.path_imgidx = path_imgidx or \
            os.path.splitext(path_imgrec)[0] + ".idx"
        if not os.path.exists(self.path_imgidx):
            raise MXNetError(
                "RecordBatchSource needs an indexed .rec: no %r "
                "(tools/rec2idx.py builds one)" % self.path_imgidx)
        keys = []
        with open(self.path_imgidx) as f:
            for line in f:
                parts = line.strip().split("\t")
                if len(parts) == 2:
                    keys.append(int(parts[0]))
        lo, hi = self._init_source(len(keys), batch_size, shuffle, seed,
                                   last_batch_handle, num_parts, part_index)
        self.keys = keys[lo:hi]
        self.data_shape = tuple(data_shape)
        self.label_width = int(label_width)
        self.aug_kwargs = dict(aug_kwargs or {})
        self._rec = None
        self._augs = None

    def __getstate__(self):
        st = dict(self.__dict__)
        st["_rec"] = None           # readers don't cross processes
        st["_augs"] = None
        st["_perm_cache"] = (None, None)
        return st

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape,
                         np.float32)]

    @property
    def provide_label(self):
        shape = (self.batch_size, self.label_width) \
            if self.label_width > 1 else (self.batch_size,)
        return [DataDesc("softmax_label", shape, np.float32)]

    def get_batch(self, epoch, index):
        from . import recordio
        from . import image as _img
        n = self.num_batches(epoch)
        if not 0 <= index < n:
            raise MXNetError("batch index %d out of range [0, %d)"
                             % (index, n))
        if self._rec is None:
            self._rec = recordio.MXIndexedRecordIO(
                self.path_imgidx, self.path_imgrec, "r")
        if self._augs is None:
            self._augs = _img.CreateAugmenter(self.data_shape,
                                              **self.aug_kwargs)
        lo = index * self.batch_size
        hi = min(lo + self.batch_size, len(self.keys))
        pad = self.batch_size - (hi - lo)
        rows = list(range(lo, hi)) + list(range(pad))
        if self.shuffle:
            perm = self._perm(epoch)
            rows = [int(perm[r]) for r in rows]
        c, h, w = self.data_shape
        data = np.zeros((self.batch_size, c, h, w), np.float32)
        label = np.zeros((self.batch_size, self.label_width), np.float32)
        # the image augmenters draw from the stdlib + numpy GLOBAL RNGs:
        # key both by stream position, restore both after — identical
        # crops/flips for any worker count, zero caller-visible drift
        py_state = _pyrandom.getstate()
        np_state = np.random.get_state()
        _pyrandom.seed(mix_seed(self.seed, epoch, index, 0x5EC))
        np.random.seed(mix_seed(self.seed, epoch, index, 0x5ED) % (2 ** 32))
        try:
            for j, r in enumerate(rows):
                header, s = recordio.unpack(self._rec.read_idx(self.keys[r]))
                img = _img.imdecode(s, 1 if c == 3 else 0, to_ndarray=False)
                for aug in self._augs:
                    img = aug(img)
                arr = np.asarray(img)
                if arr.ndim == 3:
                    arr = arr.transpose(2, 0, 1)
                data[j] = arr
                lab = np.asarray(header.label, np.float32).ravel()
                label[j, :min(lab.size, self.label_width)] = \
                    lab[:self.label_width]
        finally:
            _pyrandom.setstate(py_state)
            np.random.set_state(np_state)
        lbl = label[:, 0] if self.label_width == 1 else label
        return [data], [lbl], pad


class _EndOfEpoch(object):
    __slots__ = ()


_END = _EndOfEpoch()


class DataPipeline(DataIter):
    """Async multi-worker input pipeline with overlapped host→device
    staging — the production feed path for fused train steps.

    Three stages, each overlapping the next:

    1. **Decode** — ``num_workers`` processes (default
       ``MXNET_IO_WORKERS``) materialize batches from a picklable
       *batch source* (:class:`ArrayBatchSource`,
       :class:`RecordBatchSource`, or anything with the same
       ``provide_data``/``provide_label``/``num_batches``/``get_batch``
       surface). ``get_batch(epoch, index)`` is pure, so results
       reassemble **in order** and the stream is bitwise-identical for
       any worker count (0 = inline decode on the staging thread).
    2. **Stage** — a host thread converts each batch to device arrays
       (``jax.device_put``) into a ``prefetch``-deep buffer (default
       ``MXNET_IO_PREFETCH``), so H2D for batch N+k overlaps the
       previous step's compute. ``io.h2d`` spans and the
       ``io/pipeline_queue_depth`` gauge make the overlap visible.
    3. **Consume** — ``next()`` pops the buffer; the wait (if any) is
       the pipeline's un-hidden cost, recorded as ``io.batch_wait``
       under the step's ``train.data_wait`` span.

    Backpressure is structural: at most ``num_workers + prefetch``
    batches are in flight and at most ``prefetch`` staged, so host
    memory stays flat no matter how far the source could run ahead.

    A worker that **crashes** (preemption, native fault, an armed
    ``io.worker`` injection) is restarted in place — bounded by
    ``MXNET_IO_WORKER_RESTARTS`` — and its in-flight batches are
    re-decoded; order-keyed reassembly dedupes, so the consumer sees no
    lost and no duplicated batch.

    The cursor (:meth:`checkpoint_state` / :meth:`restore_state`)
    serializes (epoch, batch index, seed, shard identity) into the
    checkpoint manifest; restore **seeks** — nothing is decoded on the
    way — and the post-resume stream is bitwise-identical to the
    uninterrupted one.

    ``close()`` (also via ``with``) stops the stager thread and worker
    pool deterministically; it is idempotent and NOT terminal — the
    position is kept and the next use restarts lazily.
    """

    def __init__(self, source, num_workers=None, prefetch=None,
                 device_stage=True, ctx=None, restart_budget=None):
        super().__init__(int(source.batch_size))
        from . import config as _config
        self._source = source
        nw = _config.get("MXNET_IO_WORKERS") if num_workers is None \
            else num_workers
        nw = int(nw)
        if nw < 0:
            # auto: leave one core for the staging thread + train loop
            nw = max(1, (os.cpu_count() or 1) - 1)
        self._num_workers = nw
        self._depth = max(1, int(_config.get("MXNET_IO_PREFETCH")
                                 if prefetch is None else prefetch))
        self._restart_budget = int(
            _config.get("MXNET_IO_WORKER_RESTARTS")
            if restart_budget is None else restart_budget)
        self._device_stage = device_stage
        self._stage_ctx = ctx
        self._epoch = 0
        self._next_index = 0      # next batch index the consumer gets
        self._end_seen = False
        self._cond = threading.Condition()
        self._staged = deque()
        self._stop = False
        self._error = None
        self._stager = None
        self._workers = []
        self._mp_ctx = None
        self._in_q = None
        self._out_q = None
        self._trace_ctx = None
        self._current_batch = None
        # deterministic shm namespace: lets the parent reclaim segments
        # a crashed worker staged but never reported
        self._shm_prefix = "mxio-%d-%x" % (os.getpid(), id(self) & 0xFFFFFF)

    # -- provides ----------------------------------------------------------
    @property
    def provide_data(self):
        return self._source.provide_data

    @property
    def provide_label(self):
        return self._source.provide_label

    # -- lifecycle ---------------------------------------------------------
    def _ensure_running(self):
        if self._end_seen:
            return
        if self._error is not None:
            return          # deliver the pending error before restarting
        if self._stager is not None and self._stager.is_alive():
            return
        self._stop = False
        if self._num_workers > 0 and not self._workers:
            self._mp_ctx = _pipeline_mp_context()
            self._in_q = self._mp_ctx.Queue()
            self._out_q = self._mp_ctx.Queue()
            self._workers = [self._spawn_worker()
                             for _ in range(self._num_workers)]
        self._stager = threading.Thread(target=self._stager_main,
                                        name="mxnet-io-stager", daemon=True)
        self._stager.start()

    def _spawn_worker(self):
        w = self._mp_ctx.Process(
            target=_pipeline_worker_loop,
            args=(self._source, self._in_q, self._out_q,
                  self._shm_prefix), daemon=True)
        w.start()
        return w

    def _halt_segment(self):
        """Stop the stager thread; recycle the pool if the halt was
        mid-stream (in-flight tasks would leak into the next segment)."""
        st = self._stager
        self._stager = None
        if st is not None and st.is_alive():
            with self._cond:
                self._stop = True
                self._cond.notify_all()
            st.join(timeout=10.0)
        if st is not None and not self._end_seen:
            self._teardown_pool()
        with self._cond:
            self._staged.clear()
            self._stop = False
            if _tm._enabled:
                _tm.gauge("io/pipeline_queue_depth",
                          "Decoded batches staged on device ahead of the "
                          "consumer").set(0)

    def _teardown_pool(self):
        workers, self._workers = self._workers, []
        in_q, out_q = self._in_q, self._out_q
        self._in_q = None
        self._out_q = None
        if not workers:
            return
        for _ in workers:
            try:
                in_q.put_nowait(None)
            except Exception:
                pass
        # drain while workers wind down AND after: a result landing
        # mid-shutdown still holds shm segments only the consumer frees
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and \
                any(w.is_alive() for w in workers):
            try:
                _e, _i, payload, _err = out_q.get(timeout=0.2)
                _shm_unlink(payload)
            except _queue.Empty:
                pass
        for w in workers:
            w.join(timeout=1.0)
            if w.is_alive():
                w.terminate()
        while True:
            try:
                _e, _i, payload, _err = out_q.get(timeout=0.1)
                _shm_unlink(payload)
            except _queue.Empty:
                break

    def _kill_pool(self):
        """Hard-stop the pool after a worker crash: terminate everyone
        and drop the (possibly lock-poisoned) queues wholesale."""
        workers, self._workers = self._workers, []
        for w in workers:
            if w.is_alive():
                w.terminate()
        for w in workers:
            w.join(timeout=2.0)
            if w.is_alive():
                w.kill()
        for q in (self._in_q, self._out_q):
            if q is None:
                continue
            try:
                q.cancel_join_thread()
                q.close()
            except Exception:
                pass
        self._in_q = None
        self._out_q = None

    def _reclaim_segments(self, epoch, seqs):
        """Unlink segments of batches that died with their worker."""
        from multiprocessing import shared_memory
        n_leaves = len(self._source.provide_data) + \
            len(self._source.provide_label)
        for seq in seqs:
            for li in range(n_leaves):
                try:
                    seg = shared_memory.SharedMemory(
                        name="%s-%d-%d-%d" % (self._shm_prefix, epoch,
                                              seq, li))
                    seg.close()
                    seg.unlink()
                except FileNotFoundError:
                    pass
                except Exception:
                    pass

    def close(self):
        """Stop the stager thread and worker processes deterministically
        (a leaked decode process outlives SIGTERM on a TPU VM).
        Idempotent and NOT terminal: the (epoch, batch) position is
        kept and the next use restarts lazily, so a closed pipeline
        handed to a second ``fit`` just works."""
        self._halt_segment()
        self._teardown_pool()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # -- producer side (stager thread) -------------------------------------
    def _stager_main(self):
        try:
            if self._num_workers == 0:
                self._run_inline()
            else:
                self._run_pool()
        except BaseException as e:
            with self._cond:
                if self._error is None:
                    self._error = e
                self._staged.append(_END)
                self._cond.notify_all()

    def _push(self, item):
        """Bounded staging buffer: block while ``prefetch`` batches are
        already staged — the backpressure that keeps host/device memory
        flat. Returns False when the pipeline is stopping."""
        with self._cond:
            while len(self._staged) >= self._depth and not self._stop \
                    and item is not _END:
                self._cond.wait(0.1)
            if self._stop:
                return False
            self._staged.append(item)
            if _tm._enabled:
                _tm.gauge("io/pipeline_queue_depth",
                          "Decoded batches staged on device ahead of the "
                          "consumer").set(
                    sum(1 for b in self._staged if b is not _END))
            self._cond.notify_all()
            return True

    def _stage(self, data, label, pad, t0=None):
        """numpy batch -> device-resident DataBatch, from the stager
        thread: the H2D copy overlaps the consumer's compute. ``t0``
        backdates the staging window to include the shm map+copy of the
        pool transport, so io/h2d_seconds is the FULL staging cost the
        pipeline hides."""
        if t0 is None:
            t0 = _tm.monotonic()
        darr = [array(a) for a in data]
        larr = [array(a) for a in label]
        if self._device_stage:
            import jax
            from .context import current_context
            ctx = self._stage_ctx or current_context()
            dev = ctx.jax_device() if hasattr(ctx, "jax_device") else ctx
            for nd in darr + larr:
                nd._set_data(jax.device_put(nd._data, dev))
        t1 = _tm.monotonic()
        tctx = self._trace_ctx
        if tctx is not None:
            _tr.record_span("io.h2d", tctx, t0, t1)
        if _tm._enabled:
            _tm.histogram("io/h2d_seconds",
                          "Host->device staging per batch (pipeline "
                          "thread; overlaps the previous step's compute)"
                          ).observe(t1 - t0)
        return DataBatch(darr, larr, pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def _run_inline(self):
        """workers=0: decode on the staging thread. Same get_batch
        stream as the pool path — the bitwise-equality reference."""
        from . import fault as _fault
        epoch = self._epoch
        n = self._source.num_batches(epoch)
        for index in range(self._next_index, n):
            if self._stop:
                return
            _fault.inject("io.worker")
            t0 = _tm.monotonic()
            data, label, pad = self._source.get_batch(epoch, index)
            t1 = _tm.monotonic()
            tctx = self._trace_ctx
            if tctx is not None:
                _tr.record_span("io.decode", tctx, t0, t1)
            if _tm._enabled:
                _tm.histogram("io/decode_seconds",
                              "Batch decode/augment time (worker process "
                              "or inline)").observe(t1 - t0)
            if not self._push(self._stage(data, label, pad)):
                return
        self._push(_END)

    def _run_pool(self):
        epoch = self._epoch
        n = self._source.num_batches(epoch)
        next_sched = self._next_index
        next_recv = self._next_index
        window = self._num_workers + self._depth
        pending = set()          # scheduled, not yet received
        buffered = {}            # received out of order
        restarts_left = self._restart_budget
        try:
            while next_recv < n and not self._stop:
                while next_sched < n and len(pending) < window:
                    self._in_q.put((epoch, next_sched))
                    pending.add(next_sched)
                    next_sched += 1
                if next_recv in buffered:
                    t_load = _tm.monotonic()
                    data, label, pad, dt = _shm_load(buffered.pop(next_recv))
                    if _tm._enabled:
                        _tm.histogram("io/decode_seconds",
                                      "Batch decode/augment time (worker "
                                      "process or inline)").observe(dt)
                    if not self._push(self._stage(data, label, pad,
                                                  t0=t_load)):
                        return
                    next_recv += 1
                    continue
                try:
                    r_epoch, index, payload, err = \
                        self._out_q.get(timeout=0.5)
                except _queue.Empty:
                    dead = [w for w in self._workers if not w.is_alive()]
                    if not dead:
                        continue
                    if restarts_left < len(dead):
                        # same salvage-then-reclaim as the restart path,
                        # minus the respawn: a dead worker's staged-but-
                        # unreported segments are unregistered from its
                        # resource tracker, so nothing else ever frees
                        # them from /dev/shm
                        salvaged = []
                        while True:
                            try:
                                salvaged.append(
                                    self._out_q.get(timeout=0.1))
                            except _queue.Empty:
                                break
                        self._kill_pool()
                        for _se, _si, payload, _serr in salvaged:
                            _shm_unlink(payload)
                        self._reclaim_segments(epoch, pending)
                        raise MXNetError(
                            "io pipeline worker crashed and the restart "
                            "budget (MXNET_IO_WORKER_RESTARTS=%d) is "
                            "exhausted" % self._restart_budget)
                    restarts_left -= len(dead)
                    if _tm._enabled:
                        _tm.counter("io/worker_restarts_total",
                                    "Crashed input-pipeline workers "
                                    "restarted in place").inc(len(dead))
                    # a worker that died mid-queue-write leaves the
                    # SHARED pipe lock held forever, wedging every
                    # surviving worker — recycle the whole pool (fresh
                    # queues, fresh processes) instead of patching
                    # around the corpse. Landed results are salvaged
                    # first; everything scheduled-but-unreceived is
                    # re-decoded, and a task that thereby runs twice is
                    # dropped on receive (get_batch is pure) — no lost
                    # batch, no duplicated batch.
                    salvaged = []
                    while True:
                        try:
                            salvaged.append(self._out_q.get(timeout=0.1))
                        except _queue.Empty:
                            break
                    self._kill_pool()
                    # reclaim segments a dead/terminated worker staged
                    # but never reported (names are deterministic);
                    # salvaged results keep theirs — they still deliver
                    salvaged_seqs = {s[1] for s in salvaged
                                     if s[0] == epoch}
                    self._reclaim_segments(
                        epoch, pending - salvaged_seqs)
                    self._in_q = self._mp_ctx.Queue()
                    self._out_q = self._mp_ctx.Queue()
                    for item in salvaged:
                        self._out_q.put(item)
                    self._workers = [self._spawn_worker()
                                     for _ in range(self._num_workers)]
                    # salvaged seqs deliver from their re-put results;
                    # everything else is decoded again — each exactly
                    # once, so no lost and no duplicated batch
                    for seq in sorted(pending - salvaged_seqs):
                        self._in_q.put((epoch, seq))
                    continue
                pending.discard(index)
                if r_epoch != epoch or index < next_recv \
                        or index in buffered:
                    _shm_unlink(payload)   # duplicate after a restart
                    continue
                if err is not None:
                    raise MXNetError(
                        "io pipeline worker failed on batch %d: %s"
                        % (index, err))
                buffered[index] = payload
            if not self._stop:
                self._push(_END)
        finally:
            for payload in buffered.values():
                _shm_unlink(payload)

    # -- consumer side -----------------------------------------------------
    def iter_next(self):
        self._trace_ctx = _tr.active()
        self._ensure_running()
        t0 = _tm.monotonic() \
            if (_tm._enabled or self._trace_ctx is not None) else None
        with self._cond:
            while not self._staged:
                if self._error is not None:
                    break
                if self._end_seen:
                    return False
                self._cond.wait(0.5)
                if self._stager is not None \
                        and not self._stager.is_alive() \
                        and not self._staged:
                    raise MXNetError("io pipeline stager thread died "
                                     "without delivering the epoch end")
            item = self._staged.popleft() if self._staged else _END
            if _tm._enabled:
                _tm.gauge("io/pipeline_queue_depth",
                          "Decoded batches staged on device ahead of the "
                          "consumer").set(
                    sum(1 for b in self._staged if b is not _END))
            self._cond.notify_all()
        if t0 is not None:
            t1 = _tm.monotonic()
            if _tm._enabled:
                _tm.histogram(
                    "io/batch_wait_seconds",
                    "Time the consumer blocked waiting for the "
                    "prefetcher").observe(
                    t1 - t0, trace_id=self._trace_ctx.trace_id
                    if self._trace_ctx else None)
            if self._trace_ctx is not None:
                _tr.record_span("io.batch_wait", self._trace_ctx, t0, t1)
        if item is _END:
            self._current_batch = None
            if self._error is not None:
                # raise WITHOUT marking the epoch done: the position is
                # intact, so the next call retries from the failed batch
                err, self._error = self._error, None
                self._stager = None
                if isinstance(err, MXNetError):
                    raise err
                raise MXNetError("io pipeline failed: %r" % (err,))
            self._end_seen = True
            return False
        self._next_index += 1
        self._current_batch = item
        if _tm._enabled:
            _tm.counter("io/batches_total",
                        "Batches served by prefetching iterators").inc()
            _tm.counter("io/samples_total", "Samples served by "
                        "prefetching iterators").inc(
                self.batch_size - (item.pad or 0))
        return True

    def next(self):
        if self.iter_next():
            return self._current_batch
        raise StopIteration

    def getdata(self):
        return self._current_batch.data

    def getlabel(self):
        return self._current_batch.label

    def getpad(self):
        return self._current_batch.pad

    def getindex(self):
        return self._current_batch.index

    def reset(self):
        """Advance to the next epoch (NDArrayIter semantics: reset is a
        fresh pass under the next epoch's shuffle). A mid-epoch reset
        recycles the worker pool; the normal end-of-epoch reset reuses
        it."""
        self._halt_segment()
        self._epoch += 1
        self._next_index = 0
        self._end_seen = False
        self._error = None

    # -- resumable cursor --------------------------------------------------
    def checkpoint_state(self, epoch=None, nbatch=None):
        """Resumable shard cursor for the checkpoint manifest:
        (epoch, batch index, source identity incl. seed + shard).
        Restoring seeks directly — nothing is decoded on the way."""
        st = {"kind": "DataPipeline",
              "epoch": int(self._epoch if epoch is None else epoch),
              "batch": int(self._next_index if nbatch is None else nbatch)}
        fp = getattr(self._source, "cursor_fingerprint", None)
        if fp is not None:
            st["source"] = fp()
        return st

    def restore_state(self, cursor):
        """Seek to a :meth:`checkpoint_state` position: the next
        delivered batch is exactly (epoch, batch) and the stream from
        there is bitwise-identical to an uninterrupted run."""
        if cursor.get("kind") not in (None, "DataPipeline"):
            raise MXNetError("io cursor kind %r is not a DataPipeline "
                             "cursor" % cursor.get("kind"))
        saved = dict(cursor.get("source") or {})
        fp = getattr(self._source, "cursor_fingerprint", None)
        mine = fp() if fp is not None else {}
        # the seed is ADOPTED (it is part of the position); everything
        # else identifies the stream and must match
        seed = saved.pop("seed", None)
        mine.pop("seed", None)
        for key, val in saved.items():
            if key in mine and mine[key] != val:
                raise MXNetError(
                    "io cursor was taken over a stream with %s=%r but "
                    "this pipeline has %r — not the same stream"
                    % (key, val, mine[key]))
        self._halt_segment()
        self._teardown_pool()
        if seed is not None and hasattr(self._source, "set_seed"):
            self._source.set_seed(seed)
        self._epoch = int(cursor["epoch"])
        self._next_index = int(cursor.get("batch", 0))
        self._end_seen = False
        self._error = None
