"""ONNX export/import with the vendored protobuf codec.

Reference behavior: python/mxnet/contrib/onnx/ (mx2onnx export,
onnx2mx import/get_model_metadata). Round trips are validated through
an independent wire decode — the exported bytes are real opset-13
protobuf, not a private pickle.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import onnx as mxonnx


def _convnet():
    data = mx.sym.Variable("data")
    c = mx.sym.Convolution(data, kernel=(3, 3), num_filter=4, pad=(1, 1),
                           name="c1")
    b = mx.sym.BatchNorm(c, name="bn1")
    a = mx.sym.Activation(b, act_type="relu", name="r1")
    p = mx.sym.Pooling(a, kernel=(2, 2), stride=(2, 2), pool_type="max",
                       name="p1")
    f = mx.sym.FullyConnected(mx.sym.Flatten(p), num_hidden=5, name="fc")
    return mx.sym.softmax(f, name="sm")


def _bind_with_params(sym, shape, rng, params=None, aux=None):
    exe = sym.simple_bind(data=shape)
    if params is None:
        for n, arr in exe.arg_dict.items():
            if n != "data":
                arr[:] = mx.nd.array(
                    rng.randn(*arr.shape).astype(np.float32) * 0.1)
    else:
        for n, arr in params.items():
            exe.arg_dict[n][:] = arr
        for n, arr in (aux or {}).items():
            exe.aux_dict[n][:] = arr
    return exe


def test_onnx_roundtrip_convnet(tmp_path):
    rng = np.random.RandomState(0)
    sym = _convnet()
    shape = (2, 3, 8, 8)
    exe = _bind_with_params(sym, shape, rng)
    x = rng.randn(*shape).astype(np.float32)
    exe.arg_dict["data"][:] = mx.nd.array(x)
    ref = exe.forward(is_train=False)[0].asnumpy()

    path = str(tmp_path / "m.onnx")
    arg_params = {n: a for n, a in exe.arg_dict.items() if n != "data"}
    mxonnx.export_model(sym, arg_params, shape, onnx_file_path=path,
                        aux_params=dict(exe.aux_dict))

    sym2, args2, aux2 = mxonnx.import_model(path)
    exe2 = _bind_with_params(sym2, shape, rng, args2, aux2)
    exe2.arg_dict["data"][:] = mx.nd.array(x)
    out = exe2.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_onnx_metadata(tmp_path):
    rng = np.random.RandomState(1)
    sym = _convnet()
    exe = _bind_with_params(sym, (1, 3, 8, 8), rng)
    path = str(tmp_path / "meta.onnx")
    arg_params = {n: a for n, a in exe.arg_dict.items() if n != "data"}
    mxonnx.export_model(sym, arg_params, (1, 3, 8, 8),
                        onnx_file_path=path,
                        aux_params=dict(exe.aux_dict))
    meta = mxonnx.get_model_metadata(path)
    assert meta["input_tensor_data"] == [("data", (1, 3, 8, 8))]
    assert meta["output_tensor_data"][0][0] == "sm_output"


def test_onnx_wire_format_is_protobuf(tmp_path):
    """The file must be real protobuf: ir_version + opset are decodable
    by the generic wire parser, and the opset matches the spec field
    numbers (ModelProto.opset_import[0].version)."""
    rng = np.random.RandomState(2)
    data = mx.sym.Variable("data")
    f = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    exe = _bind_with_params(f, (1, 4), rng)
    path = str(tmp_path / "wire.onnx")
    mxonnx.export_model(
        f, {n: a for n, a in exe.arg_dict.items() if n != "data"},
        (1, 4), onnx_file_path=path)
    blob = open(path, "rb").read()
    fields = mxonnx._parse(blob)
    assert mxonnx._one(fields, 1) == mxonnx._IR_VERSION
    opset = mxonnx._parse(mxonnx._one(fields, 8))
    assert mxonnx._one(opset, 2) == mxonnx._OPSET
    graph = mxonnx._parse(mxonnx._one(fields, 7))
    node_ops = [mxonnx._as_str(mxonnx._one(mxonnx._parse(n), 4))
                for n in mxonnx._all(graph, 1)]
    assert node_ops == ["Flatten", "Gemm"]
    # initializers carry raw float data of the right size
    tensors = dict(mxonnx._decode_tensor(t) for t in mxonnx._all(graph, 5))
    assert tensors["fc_weight"].shape == (3, 4)


def test_onnx_elemwise_and_concat_roundtrip(tmp_path):
    rng = np.random.RandomState(3)
    a = mx.sym.Variable("data")
    h1 = mx.sym.FullyConnected(a, num_hidden=4, name="f1")
    h2 = mx.sym.Activation(h1, act_type="tanh")
    s = mx.sym.broadcast_add(h1, h2, name="add1")
    c = mx.sym.Concat(s, h2, dim=1, name="cat")
    exe = _bind_with_params(c, (2, 6), rng)
    x = rng.randn(2, 6).astype(np.float32)
    exe.arg_dict["data"][:] = mx.nd.array(x)
    ref = exe.forward(is_train=False)[0].asnumpy()

    path = str(tmp_path / "ew.onnx")
    mxonnx.export_model(
        c, {n: ar for n, ar in exe.arg_dict.items() if n != "data"},
        (2, 6), onnx_file_path=path)
    sym2, args2, aux2 = mxonnx.import_model(path)
    exe2 = _bind_with_params(sym2, (2, 6), rng, args2, aux2)
    exe2.arg_dict["data"][:] = mx.nd.array(x)
    np.testing.assert_allclose(exe2.forward(is_train=False)[0].asnumpy(),
                               ref, rtol=1e-5, atol=1e-6)


def test_onnx_import_accepts_packed_repeated_fields(tmp_path):
    """Official proto3 serializers emit packed repeated ints; the
    decoder must accept both packed and unpacked encodings."""
    from mxnet_tpu.contrib.onnx import (_f_bytes, _f_varint, _varint,
                                        _decode_tensor, _parse,
                                        _decode_attrs)
    # TensorProto with PACKED dims: field 1, wire type 2
    packed_dims = _varint(2) + _varint(3)
    t = (_f_bytes(1, packed_dims) + _f_varint(2, 1) + _f_bytes(8, "w") +
         _f_bytes(9, np.arange(6, dtype=np.float32).tobytes()))
    name, arr = _decode_tensor(t)
    assert name == "w" and arr.shape == (2, 3)
    # AttributeProto INTS with packed payload
    packed_ints = _varint(3) + _varint(3)
    a = (_f_bytes(1, "kernel_shape") + _f_bytes(8, packed_ints) +
         _f_varint(20, 7))
    node = _f_bytes(5, a)
    attrs = _decode_attrs(_parse(node))
    assert attrs["kernel_shape"] == [3, 3]


def test_onnx_fc_flatten_false_roundtrip(tmp_path):
    rng = np.random.RandomState(4)
    data = mx.sym.Variable("data")
    f = mx.sym.FullyConnected(data, num_hidden=5, flatten=False,
                              name="proj")
    exe = f.simple_bind(data=(2, 3, 4))
    for n, a in exe.arg_dict.items():
        if n != "data":
            a[:] = mx.nd.array(rng.randn(*a.shape).astype(np.float32))
    x = rng.randn(2, 3, 4).astype(np.float32)
    exe.arg_dict["data"][:] = mx.nd.array(x)
    ref = exe.forward(is_train=False)[0].asnumpy()
    assert ref.shape == (2, 3, 5)         # leading dims preserved

    path = str(tmp_path / "nf.onnx")
    mxonnx.export_model(
        f, {n: a for n, a in exe.arg_dict.items() if n != "data"},
        (2, 3, 4), onnx_file_path=path)
    sym2, args2, _aux = mxonnx.import_model(path)
    exe2 = sym2.simple_bind(data=(2, 3, 4))
    for n, a in args2.items():
        exe2.arg_dict[n][:] = a
    exe2.arg_dict["data"][:] = mx.nd.array(x)
    np.testing.assert_allclose(exe2.forward(is_train=False)[0].asnumpy(),
                               ref, rtol=1e-5, atol=1e-6)

def test_onnx_default_stride_pool_and_trained_gamma_roundtrip(tmp_path):
    """Regression: (a) Pooling with no explicit stride must round-trip
    as stride-1 (overlapping) pooling, not stride=kernel; (b) a
    BatchNorm with fix_gamma=False and a trained (non-one) gamma must
    keep that gamma through export+import; (c) a default BatchNorm
    (fix_gamma=True) must export a ones scale so external runtimes see
    the effective gamma."""
    rng = np.random.RandomState(5)
    data = mx.sym.Variable("data")
    c = mx.sym.Convolution(data, kernel=(3, 3), num_filter=4, name="c1")
    b = mx.sym.BatchNorm(c, fix_gamma=False, name="bn1")
    p = mx.sym.Pooling(b, kernel=(2, 2), pool_type="max", name="p1")
    b2 = mx.sym.BatchNorm(p, name="bn2")          # fix_gamma default True
    f = mx.sym.FullyConnected(mx.sym.Flatten(b2), num_hidden=3, name="fc")
    shape = (2, 3, 8, 8)
    exe = _bind_with_params(f, shape, rng)
    # trained, clearly-non-one gammas on BOTH bns
    exe.arg_dict["bn1_gamma"][:] = mx.nd.array(
        2.0 + rng.rand(4).astype(np.float32))
    exe.arg_dict["bn2_gamma"][:] = mx.nd.array(
        3.0 + rng.rand(4).astype(np.float32))
    x = rng.randn(*shape).astype(np.float32)
    exe.arg_dict["data"][:] = mx.nd.array(x)
    ref = exe.forward(is_train=False)[0].asnumpy()

    path = str(tmp_path / "g.onnx")
    mxonnx.export_model(
        f, {n: a for n, a in exe.arg_dict.items() if n != "data"},
        shape, onnx_file_path=path, aux_params=dict(exe.aux_dict))
    # exported scale for the fix_gamma=True bn must be ones
    blob = open(path, "rb").read()
    graph = mxonnx._parse(mxonnx._one(mxonnx._parse(blob), 7))
    tensors = dict(mxonnx._decode_tensor(t) for t in mxonnx._all(graph, 5))
    np.testing.assert_array_equal(tensors["bn2_fixed_gamma"],
                                  np.ones(4, np.float32))

    sym2, args2, aux2 = mxonnx.import_model(path)
    exe2 = _bind_with_params(sym2, shape, rng, args2, aux2)
    exe2.arg_dict["data"][:] = mx.nd.array(x)
    np.testing.assert_allclose(exe2.forward(is_train=False)[0].asnumpy(),
                               ref, rtol=1e-4, atol=1e-5)


def test_onnx_import_gemm_transb0_and_asymmetric_pads():
    """Regression: spec-default Gemm (transB=0, weight (K,N)),
    asymmetric Conv pads, and excluded-pad AveragePool must import
    correctly."""
    import os
    import tempfile
    from mxnet_tpu.contrib.onnx import (_f_bytes, _f_varint, _node,
                                        _tensor, _value_info, _wrap_attrs,
                                        _attr_ints, _attr_int, _IR_VERSION,
                                        _OPSET)

    def import_single(node_bytes, tensors, in_shape):
        body = _f_bytes(1, node_bytes)
        for tname, arr in tensors.items():
            body += _f_bytes(5, _tensor(tname, arr))
        body += _f_bytes(11, _value_info("data", in_shape))
        body += _f_bytes(12, _value_info("y", None))
        model = _f_varint(1, _IR_VERSION) + _f_bytes(7, body) + \
            _f_bytes(8, _f_bytes(1, "") + _f_varint(2, _OPSET))
        fd, path = tempfile.mkstemp(suffix=".onnx")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(model)
            return mxonnx.import_model(path)
        finally:
            os.unlink(path)

    def run(sym, args, in_shape, x):
        exe = sym.simple_bind(data=in_shape)
        for n, a in args.items():
            exe.arg_dict[n][:] = a
        exe.arg_dict["data"][:] = mx.nd.array(x)
        return exe.forward(is_train=False)[0].asnumpy()

    rng = np.random.RandomState(6)
    w = rng.randn(4, 3).astype(np.float32)           # (K, N) transB=0
    x = rng.randn(2, 4).astype(np.float32)
    gemm = _node("Gemm", ["data", "W"], ["y"], "g1")
    sym, args, _aux = import_single(gemm, {"W": w}, (2, 4))
    np.testing.assert_allclose(run(sym, args, (2, 4), x), x @ w,
                               rtol=1e-5, atol=1e-6)

    # asymmetric pads on a conv: pads=[1,0,0,1] (top,left=1,0 bot,right=0,1)
    k = np.ones((1, 1, 2, 2), np.float32)
    conv = _node("Conv", ["data", "K"], ["y"], "c1", _wrap_attrs(
        [_attr_ints("kernel_shape", [2, 2]),
         _attr_ints("strides", [1, 1]),
         _attr_ints("pads", [1, 0, 0, 1]),
         _attr_int("group", 1)]))
    sym, args, _aux = import_single(conv, {"K": k}, (1, 1, 3, 3))
    xin = rng.randn(1, 1, 3, 3).astype(np.float32)
    out = run(sym, args, (1, 1, 3, 3), xin)
    # manual reference: pad top=1,left=0, bottom=0,right=1 then valid 2x2 sum
    xp = np.pad(xin, ((0, 0), (0, 0), (1, 0), (0, 1)))
    ref = np.zeros((1, 1, 3, 3), np.float32)
    for i in range(3):
        for j in range(3):
            ref[0, 0, i, j] = xp[0, 0, i:i + 2, j:j + 2].sum()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    # excluded-pad AveragePool (count_include_pad absent -> spec default
    # 0) with asymmetric pads: border denominators count only original
    # elements
    ap = _node("AveragePool", ["data"], ["y"], "ap1", _wrap_attrs(
        [_attr_ints("kernel_shape", [2, 2]),
         _attr_ints("strides", [1, 1]),
         _attr_ints("pads", [1, 0, 0, 1])]))
    sym, args, _aux = import_single(ap, {}, (1, 1, 3, 3))
    out = run(sym, args, (1, 1, 3, 3), xin)
    xp = np.pad(xin, ((0, 0), (0, 0), (1, 0), (0, 1)))
    mask = np.pad(np.ones_like(xin), ((0, 0), (0, 0), (1, 0), (0, 1)))
    ref = np.zeros((1, 1, 3, 3), np.float32)
    for i in range(3):
        for j in range(3):
            ref[0, 0, i, j] = (xp[0, 0, i:i + 2, j:j + 2].sum()
                               / mask[0, 0, i:i + 2, j:j + 2].sum())
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
