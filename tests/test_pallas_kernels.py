"""Pallas hot-path burn-down: interpret-mode parity for the PR-17
kernels (flash prefill attention with fused page write, fused
SGD/Adam optimizer update, int8 im2col conv) plus the kernel-contract
lint and the kernel_burn_down bench job.

Every kernel under ops/pallas/ is pinned to its pure-lax twin
(PALLAS_KERNELS registry): the Pallas interpreter result must match
the twin — bitwise for integer math and page copies, ULP-bounded for
float update rules, allclose at float32 round-off for online-softmax
attention — and the off-TPU default dispatch must BE the twin (so
tier-1 CPU numerics never change).
"""
import importlib.util
import os

import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401  (registers nd ops)

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from mxnet_tpu.ops.pallas.flash_attention import (  # noqa: E402
    _flash_fwd_xla, _flash_prefill_xla, flash_attention,
    flash_prefill_paged)
from mxnet_tpu.ops.pallas import fused_update as fu  # noqa: E402
from mxnet_tpu.ops.pallas.fused_update import (  # noqa: E402
    _adam_fused_xla, _sgd_fused_xla, adam_fused_update, sgd_fused_update)
from mxnet_tpu.ops.pallas.int8_matmul import (  # noqa: E402
    _int8_conv_xla, int8_conv_im2col)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# flash attention (dense forward)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("b,h,s,d,causal", [
    (2, 4, 64, 32, True),
    (1, 2, 128, 16, False),
    (2, 3, 96, 8, True),
])
def test_flash_attention_interpret_matches_twin(b, h, s, d, causal):
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, h, s, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, h, s, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, h, s, d).astype(np.float32))
    o = flash_attention(q, k, v, causal=causal, interpret=True)
    ref, _ = _flash_fwd_xla(q, k, v, causal, 1.0 / d ** 0.5)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# flash prefill attention + fused page write
# ---------------------------------------------------------------------------

def _prefill_case(seed, b, s, nh, kvh, hd, ps, num_pages):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, s, nh, hd).astype(np.float32))
    kg = jnp.asarray(rng.randn(b, s, kvh, hd).astype(np.float32))
    vg = jnp.asarray(rng.randn(b, s, kvh, hd).astype(np.float32))
    kp = jnp.asarray(rng.randn(num_pages, ps, kvh, hd).astype(np.float32))
    vp = jnp.asarray(rng.randn(num_pages, ps, kvh, hd).astype(np.float32))
    n_pb = s // ps
    # distinct pages per row, leaving some pages untouched
    bt = jnp.asarray(
        np.arange(b * n_pb, dtype=np.int32).reshape(b, n_pb))
    return q, kg, vg, kp, vp, bt


@pytest.mark.parametrize("b,s,nh,kvh,hd,ps", [
    (2, 32, 4, 2, 16, 8),    # GQA, 4 pages/row
    (1, 16, 2, 2, 8, 16),    # MHA, single page/row
    (2, 24, 6, 3, 8, 8),     # 3 kv heads, non-pow2 bucket
])
def test_flash_prefill_interpret_matches_twin(b, s, nh, kvh, hd, ps):
    num_pages = 2 * b * (s // ps) + 3
    q, kg, vg, kp, vp, bt = _prefill_case(1, b, s, nh, kvh, hd, ps,
                                          num_pages)
    o, kp_n, vp_n = flash_prefill_paged(q, kg, vg, kp, vp, bt,
                                        interpret=True)
    ox, kpx, vpx = _flash_prefill_xla(q, kg, vg, kp, vp, bt)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ox),
                               rtol=2e-5, atol=2e-5)
    # the fused page-write epilogue is bitwise: pages are copied, not
    # recomputed
    np.testing.assert_array_equal(np.asarray(kp_n), np.asarray(kpx))
    np.testing.assert_array_equal(np.asarray(vp_n), np.asarray(vpx))
    # untouched pool pages are preserved via in->out aliasing
    touched = set(np.asarray(bt).ravel().tolist())
    for p in range(num_pages):
        if p not in touched:
            np.testing.assert_array_equal(np.asarray(kp_n[p]),
                                          np.asarray(kp[p]))


def test_flash_prefill_default_dispatch_is_twin_off_tpu():
    if jax.default_backend() == "tpu":
        pytest.skip("off-TPU dispatch contract")
    q, kg, vg, kp, vp, bt = _prefill_case(2, 2, 32, 4, 2, 16, 8, 11)
    o, kp_n, vp_n = flash_prefill_paged(q, kg, vg, kp, vp, bt)
    ox, kpx, vpx = _flash_prefill_xla(q, kg, vg, kp, vp, bt)
    np.testing.assert_array_equal(np.asarray(o), np.asarray(ox))
    np.testing.assert_array_equal(np.asarray(kp_n), np.asarray(kpx))
    np.testing.assert_array_equal(np.asarray(vp_n), np.asarray(vpx))


def test_flash_prefill_null_page_warmup_row():
    """The decode warmup batch maps every page slot to page 0: both the
    kernel DMA (sequential over ki then j) and the twin's scatter are
    last-write-wins, so page 0 must hold the LAST position block and
    the pools must still agree bitwise."""
    b, s, nh, kvh, hd, ps = 1, 32, 4, 2, 16, 8
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(b, s, nh, hd).astype(np.float32))
    kg = jnp.asarray(rng.randn(b, s, kvh, hd).astype(np.float32))
    vg = jnp.asarray(rng.randn(b, s, kvh, hd).astype(np.float32))
    kp = jnp.asarray(rng.randn(6, ps, kvh, hd).astype(np.float32))
    vp = jnp.asarray(rng.randn(6, ps, kvh, hd).astype(np.float32))
    bt = jnp.zeros((b, s // ps), jnp.int32)
    o, kp_n, vp_n = flash_prefill_paged(q, kg, vg, kp, vp, bt,
                                        interpret=True)
    ox, kpx, vpx = _flash_prefill_xla(q, kg, vg, kp, vp, bt)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ox),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_array_equal(np.asarray(kp_n), np.asarray(kpx))
    np.testing.assert_array_equal(np.asarray(vp_n), np.asarray(vpx))
    np.testing.assert_array_equal(np.asarray(kp_n[0]),
                                  np.asarray(kg[0, -ps:]))
    # pages beyond slot 0 keep their prior contents
    np.testing.assert_array_equal(np.asarray(kp_n[1:]),
                                  np.asarray(kp[1:]))


def test_flash_prefill_validations():
    q, kg, vg, kp, vp, bt = _prefill_case(4, 1, 16, 2, 2, 8, 16, 5)
    with pytest.raises(ValueError, match="not a multiple of page_size"):
        flash_prefill_paged(q[:, :12], kg[:, :12], vg[:, :12],
                            kp, vp, bt)
    with pytest.raises(ValueError, match="pages/row"):
        flash_prefill_paged(q, kg, vg, kp, vp, bt[:, :0])


# ---------------------------------------------------------------------------
# fused optimizer update
# ---------------------------------------------------------------------------

_SGD_H = {"lr": 0.05, "wd": 1e-4, "rescale_grad": 1.0 / 32,
          "momentum": 0.9, "clip_gradient": 1.0}
_ADAM_H = {"lr": 1e-3, "wd": 1e-4, "rescale_grad": 1.0,
           "beta1": 0.9, "one_minus_beta1": 0.1,
           "beta2": 0.999, "one_minus_beta2": 0.001,
           "epsilon": 1e-8}


def _wg(seed, shape):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.randn(*shape).astype(np.float32)),
            jnp.asarray(rng.randn(*shape).astype(np.float32)),
            jnp.asarray(rng.randn(*shape).astype(np.float32)))


def _check_update(rule, h, w, g, state, out_w, out_s):
    """Interpret-mode parity vs the jitted lax twin, pinned in ULPs:
    XLA:CPU's FMA-contraction choices depend on operand shape/layout,
    so the interpreter's (rows, 128) ref plumbing can shift state by a
    ULP, which ``w + mom`` amplifies to a few ULPs of the (smaller)
    weight. The BITWISE guarantee lives in the dispatcher — off-TPU the
    public entry points run the twin itself (asserted in
    test_fused_rule_knob_selects_pallas)."""
    ref_w, ref_s = jax.jit(
        lambda w, g, s: rule(w, g, s, h))(w, g, tuple(state))
    np.testing.assert_array_max_ulp(np.asarray(out_w),
                                    np.asarray(ref_w), maxulp=16)
    for a, b in zip(out_s, ref_s):
        np.testing.assert_array_max_ulp(np.asarray(a), np.asarray(b),
                                        maxulp=2)


@pytest.mark.parametrize("shape", [(1,), (7,), (128, 130), (3, 5, 17)])
def test_sgd_fused_update_interpret_parity(shape):
    w, g, m = _wg(5, shape)
    out_w, out_s = sgd_fused_update(w, g, (m,), _SGD_H, interpret=True)
    _check_update(_sgd_fused_xla, _SGD_H, w, g, (m,), out_w, out_s)


def test_sgd_fused_update_stateless_interpret_parity():
    h = {"lr": 0.05, "wd": 1e-4, "rescale_grad": 1.0}
    w, g, _ = _wg(6, (33, 9))
    out_w, out_s = sgd_fused_update(w, g, (), h, interpret=True)
    assert out_s == ()
    _check_update(_sgd_fused_xla, h, w, g, (), out_w, out_s)


@pytest.mark.parametrize("shape", [(1,), (64, 33), (2, 3, 40)])
def test_adam_fused_update_interpret_parity(shape):
    w, g, mean = _wg(7, shape)
    var = jnp.abs(_wg(8, shape)[0])
    out_w, out_s = adam_fused_update(w, g, (mean, var), _ADAM_H,
                                     interpret=True)
    _check_update(_adam_fused_xla, _ADAM_H, w, g, (mean, var),
                  out_w, out_s)


def test_fused_update_hyper_change_no_recompile():
    """Hypers ride in as a stacked f32 vector, so sweeping lr/wd must
    not grow the jit cache (the zero-compiles-after-warmup contract of
    the fused train step)."""
    w, g, m = _wg(9, (64, 33))
    h = dict(_SGD_H)
    sgd_fused_update(w, g, (m,), h, interpret=True)
    size = fu._fused_update._cache_size()
    for lr in (0.1, 0.01, 0.003):
        h = dict(h, lr=lr, wd=lr / 10)
        sgd_fused_update(w, g, (m,), h, interpret=True)
    assert fu._fused_update._cache_size() == size


def test_fused_rule_knob_selects_pallas(monkeypatch):
    from mxnet_tpu.optimizer import (Adam, SGD, _adam_fused,
                                     _adam_fused_pallas, _sgd_fused,
                                     _sgd_fused_pallas)
    monkeypatch.setenv("MXNET_PALLAS_FUSED_UPDATE", "0")
    assert SGD(momentum=0.9).fused_rule() is _sgd_fused
    assert Adam().fused_rule() is _adam_fused
    monkeypatch.setenv("MXNET_PALLAS_FUSED_UPDATE", "1")
    assert SGD(momentum=0.9).fused_rule() is _sgd_fused_pallas
    assert Adam().fused_rule() is _adam_fused_pallas
    # off-TPU the pallas rule dispatches straight to the lax rule, so
    # tier-1 training numerics are bitwise-unchanged by the knob
    if jax.default_backend() != "tpu":
        w, g, m = _wg(10, (17, 5))
        a_w, a_s = _sgd_fused_pallas(w, g, (m,), _SGD_H)
        b_w, b_s = _sgd_fused(w, g, (m,), _SGD_H)
        np.testing.assert_array_equal(np.asarray(a_w), np.asarray(b_w))
        np.testing.assert_array_equal(np.asarray(a_s[0]),
                                      np.asarray(b_s[0]))


# ---------------------------------------------------------------------------
# int8 im2col conv
# ---------------------------------------------------------------------------

def _conv_case(seed, b, cin, hw, cout, k, zero_channel=False):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(
        rng.randint(-127, 128, (b, cin, hw, hw)).astype(np.int8))
    wq = jnp.asarray(
        rng.randint(-127, 128, (cout,) + k).astype(np.int8))
    scale = rng.rand(cout).astype(np.float32) * 0.01 + 1e-4
    if zero_channel:
        scale[1] = 0.0
    return q, wq, jnp.asarray(scale)


@pytest.mark.parametrize(
    "cin,hw,cout,kh,stride,dilate,pad,groups,zero_ch", [
        (3, 8, 4, 3, (1, 1), (1, 1), (0, 0), 1, False),
        (4, 9, 6, 3, (2, 2), (2, 2), (1, 1), 2, False),
        (3, 7, 4, 1, (1, 1), (1, 1), (0, 0), 1, False),
        (2, 8, 4, 3, (1, 1), (1, 1), (1, 1), 1, True),
    ])
def test_int8_conv_im2col_interpret_bitwise(cin, hw, cout, kh, stride,
                                            dilate, pad, groups,
                                            zero_ch):
    q, wq, scale = _conv_case(11, 2, cin, hw, cout,
                              (cin // groups, kh, kh), zero_ch)
    out = int8_conv_im2col(q, wq, scale, stride, dilate, pad,
                           num_group=groups, interpret=True)
    ref = _int8_conv_xla(q, wq, scale, stride, dilate, pad, groups)
    # int32 accumulation + one f32 rescale on both routes -> bitwise
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    if zero_ch:
        np.testing.assert_array_equal(np.asarray(out[:, 1]), 0.0)


def test_quantized_conv_int8_op_im2col_route(monkeypatch):
    """MXNET_INT8_CONV_IM2COL=1 swaps _contrib_quantized_conv_int8 onto
    the im2col-MXU route; off-TPU both routes are exact int32 conv +
    per-channel rescale, so the op output must be bitwise identical."""
    from mxnet_tpu.ops.registry import get_op
    from mxnet_tpu.quantize.ptq import _per_channel_quantize
    rng = np.random.RandomState(12)
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    w = rng.randn(4, 3, 3, 3).astype(np.float32) * 0.3
    bias = rng.randn(4).astype(np.float32)
    wq, ws = _per_channel_quantize(w)
    op = get_op("_contrib_quantized_conv_int8").fn
    kw = dict(kernel=(3, 3), num_filter=4,
              act_scale=float(127.0 / np.abs(x).max()))
    monkeypatch.delenv("MXNET_INT8_CONV_IM2COL", raising=False)
    ref = np.asarray(op(jnp.asarray(x), jnp.asarray(wq),
                        jnp.asarray(ws), jnp.asarray(bias), **kw))
    monkeypatch.setenv("MXNET_INT8_CONV_IM2COL", "1")
    out = np.asarray(op(jnp.asarray(x), jnp.asarray(wq),
                        jnp.asarray(ws), jnp.asarray(bias), **kw))
    np.testing.assert_array_equal(out, ref)


# ---------------------------------------------------------------------------
# kernel contract lint + bench job + prefill variant tag
# ---------------------------------------------------------------------------

def test_kernel_contract_lint():
    spec = importlib.util.spec_from_file_location(
        "check_pallas_contracts",
        os.path.join(ROOT, "tools", "check_pallas_contracts.py"))
    modl = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(modl)
    drift = modl.check()
    assert all(not v for v in drift.values()), drift


def test_kernel_burn_down_job_registered():
    from mxnet_tpu import benchmark
    assert "kernel_burn_down" in benchmark.JOBS
    assert "kernel_burn_down" in benchmark.JOB_PRIORITY
    assert callable(benchmark.kernel_burn_down)


def test_prefill_variant_tag_in_program_key():
    from mxnet_tpu.serve.decode import _prefill_variant
    from mxnet_tpu.programs import ProgramKey
    if jax.default_backend() != "tpu":
        assert _prefill_variant() == "xla-prefill"
    tagged = ProgramKey("decode_prefill", "g",
                        {"bucket": 128, "kernel": _prefill_variant()})
    untagged = ProgramKey("decode_prefill", "g", {"bucket": 128})
    assert tagged.fingerprint != untagged.fingerprint
