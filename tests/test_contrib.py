"""contrib package: quantization driver, text, svrg, tensorboard, onnx."""
import collections
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError


# ---------------------------------------------------------------------------
# quantization driver
# ---------------------------------------------------------------------------

def _mlp_sym():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    h = mx.sym.Activation(h, act_type="relu", name="relu1")
    h = mx.sym.FullyConnected(h, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(h, name="softmax")


class _Batches(object):
    """Minimal calib iterable with .data batches."""

    def __init__(self, arrays):
        self._arrays = arrays

    def __iter__(self):
        for a in self._arrays:
            yield type("B", (), {"data": [a]})()


def _fit_fp32(sym, X, Y):
    exe = sym.simple_bind(data=(X.shape[0], X.shape[1]))
    rng = np.random.RandomState(0)
    for n, arr in exe.arg_dict.items():
        if n not in ("data", "softmax_label"):
            arr[:] = mx.nd.array(
                rng.randn(*arr.shape).astype(np.float32) * 0.2)
    exe.arg_dict["data"][:] = mx.nd.array(X)
    return exe


def test_quantize_model_numeric_close():
    rng = np.random.RandomState(0)
    X = rng.randn(8, 10).astype(np.float32)
    sym = _mlp_sym()
    exe = _fit_fp32(sym, X, None)
    fp32_out = exe.forward(is_train=False)[0].asnumpy()
    arg_params = {n: a.copy() for n, a in exe.arg_dict.items()
                  if n not in ("data", "softmax_label")}

    calib = _Batches([mx.nd.array(X)])
    qsym, qarg, qaux = mx.contrib.quantize_model(
        sym, arg_params, {}, data_names=("data",), calib_mode="naive",
        calib_data=calib)
    ops = {n.op for n in
           __import__("mxnet_tpu.symbol.symbol",
                      fromlist=["_topo"])._topo(qsym._entries)
           if not n.is_var}
    assert "_contrib_quantized_fully_connected" in ops
    assert "_contrib_quantize_v2" in ops
    qexe = qsym.simple_bind(data=(8, 10))
    for n, v in qarg.items():
        if n in qexe.arg_dict:
            qexe.arg_dict[n][:] = v
    qexe.arg_dict["data"][:] = mx.nd.array(X)
    int8_out = qexe.forward(is_train=False)[0].asnumpy()
    # int8 probabilities track fp32 within quantization error
    assert np.max(np.abs(int8_out - fp32_out)) < 0.06, \
        np.max(np.abs(int8_out - fp32_out))


def test_quantize_model_excluded_layer():
    sym = _mlp_sym()
    rng = np.random.RandomState(1)
    arg_params = {"fc1_weight": mx.nd.array(rng.randn(16, 10) * 0.1),
                  "fc1_bias": mx.nd.zeros((16,)),
                  "fc2_weight": mx.nd.array(rng.randn(4, 16) * 0.1),
                  "fc2_bias": mx.nd.zeros((4,))}
    qsym, _, _ = mx.contrib.quantize_model(
        sym, arg_params, {}, excluded_sym_names=("fc1",),
        calib_mode="none")
    from mxnet_tpu.symbol.symbol import _topo
    names = {n.name: n.op for n in _topo(qsym._entries) if not n.is_var}
    assert names.get("fc1") == "FullyConnected"       # kept fp32
    assert "fc2_quantized" in names                   # quantized


def test_quantize_model_unknown_excluded_raises():
    """Satellite: a typo'd excluded_sym_names entry must raise an
    MXNetError NAMING the stranger instead of silently quantizing the
    layer it meant to protect."""
    sym = _mlp_sym()
    with pytest.raises(MXNetError, match="fc_zap"):
        mx.contrib.quantize_model(
            sym, {"fc1_weight": mx.nd.zeros((16, 10))}, {},
            excluded_sym_names=("fc_zap",), calib_mode="none")


def test_quantize_model_calib_mode_validation():
    """Satellite: an unknown calib_mode raises instead of silently
    serving naive ranges; naive/entropy without calib_data raise."""
    sym = _mlp_sym()
    with pytest.raises(MXNetError, match="calib_mode"):
        mx.contrib.quantize_model(sym, {}, {}, calib_mode="zapcalib")
    with pytest.raises(MXNetError, match="calib_data"):
        mx.contrib.quantize_model(sym, {}, {}, calib_mode="entropy")
    with pytest.raises(MXNetError, match="calib_data"):
        mx.contrib.quantize_model(sym, {}, {}, calib_mode="naive")


def test_quantize_model_entropy_routes_to_percentile(monkeypatch):
    """Satellite: calib_mode='entropy' now runs the percentile
    observer (quantize/calibrate.py) — an outlier activation no longer
    defines the whole calibrated range the way naive min/max does."""
    from mxnet_tpu.symbol.symbol import _topo
    monkeypatch.setenv("MXNET_QUANT_PERCENTILE", "90")
    rng = np.random.RandomState(0)
    X = rng.randn(32, 10).astype(np.float32)
    X[0, 0] = 1000.0                     # one absurd outlier
    sym = _mlp_sym()
    exe = _fit_fp32(sym, X, None)
    arg_params = {n: a.copy() for n, a in exe.arg_dict.items()
                  if n not in ("data", "softmax_label")}
    calib = _Batches([mx.nd.array(X)])

    def data_quantize_range(calib_mode):
        qsym, _, _ = mx.contrib.quantize_model(
            sym, arg_params, {}, data_names=("data",),
            calib_mode=calib_mode, calib_data=_Batches([mx.nd.array(X)]))
        # the quantize_v2 node fed by the raw data variable carries the
        # calibrated range as attrs
        for n in _topo(qsym._entries):
            if n.op == "_contrib_quantize_v2" \
                    and n.inputs[0][0].name == "data":
                return float(n.attrs["max_calib_range"])
        raise AssertionError("no quantize_v2 node over data")

    naive = data_quantize_range("naive")
    entropy = data_quantize_range("entropy")
    assert naive == pytest.approx(1000.0)      # min/max eats the outlier
    assert entropy < 100.0, entropy            # percentile clips it


def test_collect_ranges_executor_cache_and_merge():
    """Satellite: mixed batch shapes through _collect_ranges bind ONE
    executor per distinct shape (telemetry-counted) and ranges merge
    across every batch, whichever executor ran it."""
    from mxnet_tpu import telemetry as tm
    from mxnet_tpu.contrib.quantization import _collect_ranges
    sym = _mlp_sym()
    rng = np.random.RandomState(3)
    arg_params = {"fc1_weight": mx.nd.array(rng.randn(16, 10) * 0.1),
                  "fc1_bias": mx.nd.zeros((16,)),
                  "fc2_weight": mx.nd.array(rng.randn(4, 16) * 0.1),
                  "fc2_bias": mx.nd.zeros((4,))}
    b1 = np.full((8, 10), 2.0, np.float32)       # shape A
    b2 = np.full((4, 10), -7.0, np.float32)      # shape B
    b3 = np.full((8, 10), 5.0, np.float32)       # shape A again: reuse
    binds0 = tm.counter("quantize/calib_binds_total").value
    stats = _collect_ranges(sym, arg_params, {},
                            _Batches([mx.nd.array(b) for b in
                                      (b1, b2, b3)]),
                            ["data"], ["softmax_label"])
    # 3 batches, 2 distinct shapes -> exactly 2 executor binds
    assert tm.counter("quantize/calib_binds_total").value - binds0 == 2
    # ranges merged across ALL batches (including the cache-hit one)
    assert stats[("data", 0)] == (-7.0, 5.0)


# ---------------------------------------------------------------------------
# text
# ---------------------------------------------------------------------------

def test_text_vocabulary():
    from mxnet_tpu.contrib import text
    counter = text.count_tokens_from_str("a b b c c c")
    vocab = text.Vocabulary(counter, min_freq=2)
    assert len(vocab) == 3                            # <unk>, c, b
    assert vocab.to_indices("c") == 1
    assert vocab.to_indices(["b", "zzz"]) == [2, 0]
    assert vocab.to_tokens([1, 2]) == ["c", "b"]


def test_text_custom_embedding():
    from mxnet_tpu.contrib import text
    emb = text.CustomEmbedding(vectors={"hello": [1.0, 2.0],
                                        "world": [3.0, 4.0]})
    v = emb.get_vecs_by_tokens(["hello", "nope"])
    np.testing.assert_allclose(v.asnumpy(), [[1, 2], [0, 0]])
    emb.update_token_vectors("world", mx.nd.array([[9.0, 9.0]]))
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("world").asnumpy(), [9, 9])


def test_text_pretrained_gated():
    from mxnet_tpu.contrib import text
    with pytest.raises(MXNetError):
        text.GloVe()


# ---------------------------------------------------------------------------
# svrg
# ---------------------------------------------------------------------------

def test_svrg_module_converges():
    from mxnet_tpu.contrib.svrg_optimization import SVRGModule
    from mxnet_tpu.io import NDArrayIter
    rng = np.random.RandomState(2)
    X = rng.randn(64, 8).astype(np.float32)
    w_true = rng.randn(8, 4).astype(np.float32)
    Y = np.argmax(X @ w_true, axis=1).astype(np.float32)
    it = NDArrayIter(X, Y, batch_size=16, label_name="softmax_label")
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    sym = mx.sym.SoftmaxOutput(fc, name="softmax")
    mod = SVRGModule(sym, update_freq=1)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    em = mod.fit(it, num_epoch=6, lr=0.2)
    assert em.get_name_value()[0][1] > 0.8, em.get_name_value()


# ---------------------------------------------------------------------------
# tensorboard + onnx gating
# ---------------------------------------------------------------------------

def test_tensorboard_fallback_jsonl(tmp_path):
    from mxnet_tpu.contrib.tensorboard import LogMetricsCallback
    from mxnet_tpu import metric as _metric
    cb = LogMetricsCallback(str(tmp_path))
    m = _metric.create("acc")
    m.update([mx.nd.array([1.0, 0.0])],
             [mx.nd.array([[0.1, 0.9], [0.8, 0.2]])])
    param = type("P", (), {"eval_metric": m, "nbatch": 3, "epoch": 0})()
    cb(param)
    logged = os.path.join(str(tmp_path), "metrics.jsonl")
    if cb._writer is None:
        assert os.path.exists(logged)
        assert "accuracy" in open(logged).read()


def test_onnx_unsupported_op_raises_cleanly():
    d = mx.sym.Variable("data")
    bad = mx.sym.arccos(d)          # outside the converter subset
    with pytest.raises(MXNetError):
        mx.contrib.onnx.export_model(bad, {}, (1, 4),
                                     onnx_file_path=None)


def test_contrib_autograd_legacy_surface():
    """Old experimental autograd API (reference: contrib/autograd.py):
    grad_and_loss / grad decorators over the first-class tape."""
    from mxnet_tpu.contrib import autograd as cag

    x = mx.nd.array(np.array([1.0, 2.0, 3.0], np.float32))

    @cag.grad_and_loss
    def loss_fn(x):
        return (x * x).sum()

    grads, loss = loss_fn(x)
    np.testing.assert_allclose(grads[0].asnumpy(), [2.0, 4.0, 6.0],
                               rtol=1e-6)
    assert abs(float(loss.asnumpy()) - 14.0) < 1e-5

    g_only = cag.grad(loss_fn.__wrapped__)(x)
    np.testing.assert_allclose(g_only[0].asnumpy(), [2.0, 4.0, 6.0],
                               rtol=1e-6)

    with cag.train_section():
        assert mx.autograd.is_recording()
    with cag.test_section():
        assert not mx.autograd.is_training()


def test_contrib_dataloader_iter():
    """DataLoaderIter adapts a gluon DataLoader to the DataIter
    protocol (reference: contrib/io.py) — Module.fit consumes it."""
    from mxnet_tpu.contrib.io import DataLoaderIter
    from mxnet_tpu.gluon.data import DataLoader
    from mxnet_tpu.gluon.data.dataset import ArrayDataset

    rng = np.random.RandomState(0)
    data = rng.randn(20, 4).astype(np.float32)
    labels = rng.randint(0, 2, 20).astype(np.float32)
    dl = DataLoader(ArrayDataset(data, labels), batch_size=5)
    it = DataLoaderIter(dl)
    assert it.provide_data[0].shape == (5, 4)
    seen = 0
    for batch in it:
        seen += batch.data[0].shape[0]
    assert seen == 20
    it.reset()
    assert next(it).data[0].shape == (5, 4)
