// Fluent symbol-building sugar for the C++ frontend.
// Capability analog of the reference's cpp-package/include/mxnet-cpp/
// operator.h: Operator("Convolution").SetParam(...).SetInput(...)
// .CreateSymbol(name) — the idiom every mxnet-cpp example composes
// networks with. Builds on the two-phase atomic+compose C ABI.
#ifndef MXNET_TPU_CPP_OPERATOR_HPP_
#define MXNET_TPU_CPP_OPERATOR_HPP_

#include <map>
#include <sstream>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

#include "mxnet_tpu_cpp/executor.hpp"

namespace mxnet_tpu_cpp {

class Operator {
 public:
  explicit Operator(std::string op_name) : op_name_(std::move(op_name)) {}

  // streamed like the reference's mxnet-cpp template SetParam, so any
  // arithmetic type works without overload ambiguity
  template <typename T>
  Operator& SetParam(const std::string& key, const T& value) {
    std::ostringstream os;
    os << value;
    params_[key] = os.str();
    return *this;
  }
  Operator& SetParam(const std::string& key, bool value) {
    params_[key] = value ? "True" : "False";
    return *this;
  }

  // named input wired into the op's matching slot at CreateSymbol;
  // rvalues are rejected at compile time — the Symbol must outlive
  // CreateSymbol (its handle is borrowed, not copied)
  Operator& SetInput(const std::string& name, const Symbol& sym) {
    inputs_.emplace_back(name, &sym);
    return *this;
  }
  Operator& SetInput(const std::string&, Symbol&&) = delete;

  // positional sugar: unnamed inputs wire in order into the op's free
  // slots (lhs/rhs, data, ... — the compose fallback)
  Operator& operator()(const Symbol& sym) {
    inputs_.emplace_back(std::string(), &sym);
    return *this;
  }
  Operator& operator()(Symbol&&) = delete;

  Symbol CreateSymbol(const std::string& name = "") {
    Symbol s = Symbol::Atomic(op_name_, params_, name);
    if (inputs_.empty()) return s;
    bool any_named = false, any_positional = false;
    for (const auto& kv : inputs_)
      (kv.first.empty() ? any_positional : any_named) = true;
    if (any_named && any_positional)
      throw std::invalid_argument(
          "Operator: mixing named SetInput and positional operator() "
          "inputs is ambiguous");
    if (any_positional) {
      std::vector<const Symbol*> args;
      for (const auto& kv : inputs_) args.push_back(kv.second);
      s.ComposePositional(args, name);
    } else {
      std::map<std::string, const Symbol*> wired;
      for (const auto& kv : inputs_) {
        if (!wired.emplace(kv.first, kv.second).second)
          throw std::invalid_argument(
              "Operator: duplicate input name '" + kv.first + "'");
      }
      s.Compose(wired, name);
    }
    return s;
  }

 private:
  std::string op_name_;
  std::map<std::string, std::string> params_;
  // pointers borrowed until CreateSymbol; caller keeps inputs alive
  std::vector<std::pair<std::string, const Symbol*>> inputs_;
};

}  // namespace mxnet_tpu_cpp

#endif  // MXNET_TPU_CPP_OPERATOR_HPP_
