"""Symbolic MobileNet v1 (capability parity with
example/image-classification/symbols/mobilenet.py; architecture per
Howard et al. 2017 — depthwise-separable convolutions).
"""
from __future__ import annotations

from .. import symbol as sym

__all__ = ["get_symbol"]


def _conv_block(x, name, num_filter, kernel=(3, 3), stride=(1, 1),
                pad=(1, 1), num_group=1):
    x = sym.Convolution(x, name=name, num_filter=num_filter, kernel=kernel,
                        stride=stride, pad=pad, num_group=num_group,
                        no_bias=True)
    x = sym.BatchNorm(x, name=name + "_bn", fix_gamma=False)
    return sym.Activation(x, name=name + "_relu", act_type="relu")


def _dw_sep(x, name, in_ch, out_ch, stride=(1, 1), multiplier=1.0):
    in_ch = int(in_ch * multiplier)
    out_ch = int(out_ch * multiplier)
    x = _conv_block(x, name + "_dw", in_ch, kernel=(3, 3), stride=stride,
                    pad=(1, 1), num_group=in_ch)
    return _conv_block(x, name + "_pw", out_ch, kernel=(1, 1),
                       stride=(1, 1), pad=(0, 0))


def get_symbol(num_classes=1000, multiplier=1.0, dtype="float32"):
    data = sym.Variable("data")
    x = _conv_block(data, "conv0", int(32 * multiplier), stride=(2, 2))
    cfg = [  # (in, out, stride)
        (32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
        (256, 256, 1), (256, 512, 2),
        (512, 512, 1), (512, 512, 1), (512, 512, 1), (512, 512, 1),
        (512, 512, 1),
        (512, 1024, 2), (1024, 1024, 1),
    ]
    for i, (cin, cout, s) in enumerate(cfg):
        x = _dw_sep(x, "sep%d" % (i + 1), cin, cout, stride=(s, s),
                    multiplier=multiplier)
    x = sym.Pooling(x, name="pool", global_pool=True, kernel=(7, 7),
                    pool_type="avg")
    x = sym.Flatten(x)
    x = sym.FullyConnected(x, name="fc", num_hidden=num_classes)
    return sym.SoftmaxOutput(x, name="softmax")
