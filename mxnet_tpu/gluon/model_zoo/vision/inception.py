"""Inception V3 for the gluon model zoo.

Capability parity with the reference zoo
(python/mxnet/gluon/model_zoo/vision/inception.py), same parameter
names so published ``.params`` files load.

The topology is written as data, not builder functions: ``_STEM`` and
``_STAGES`` below spell out every conv (channels/kernel/stride/pad) and
pool of the network, and a small interpreter turns rows into blocks.
The 17x17->8x8 "expanded" tail blocks (whose inner branches fork and
re-concat) carry their fork structure in the same table via nested
branch lists.
"""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn
from ..model_store import get_model_file

__all__ = ["Inception3", "inception_v3"]


def _c(channels, kernel, stride=1, pad=0):
    """One conv row of the topology table."""
    return {"channels": channels, "kernel_size": kernel,
            "strides": stride, "padding": pad}


def _cbr(cfg):
    """conv -> BN(eps 1e-3) -> relu, the network's only conv unit."""
    unit = nn.HybridSequential(prefix="")
    unit.add(nn.Conv2D(use_bias=False, **cfg))
    unit.add(nn.BatchNorm(epsilon=0.001))
    unit.add(nn.Activation("relu"))
    return unit


def _branch(rows):
    """A branch: optional leading pool marker, then conv rows."""
    seq = nn.HybridSequential(prefix="")
    for row in rows:
        if row == "avgpool":
            seq.add(nn.AvgPool2D(pool_size=3, strides=1, padding=1))
        elif row == "maxpool":
            seq.add(nn.MaxPool2D(pool_size=3, strides=2))
        else:
            seq.add(_cbr(row))
    return seq


# Stem: 299x299x3 -> 35x35x192
_STEM = (_c(32, 3, stride=2), _c(32, 3), _c(64, 3, pad=1), "maxpool",
         _c(80, 1), _c(192, 3), "maxpool")


def _a_stage(prefix, pool_features):
    return (prefix, [
        [_c(64, 1)],
        [_c(48, 1), _c(64, 5, pad=2)],
        [_c(64, 1), _c(96, 3, pad=1), _c(96, 3, pad=1)],
        ["avgpool", _c(pool_features, 1)],
    ])


def _c_stage(prefix, mid):
    return (prefix, [
        [_c(192, 1)],
        [_c(mid, 1), _c(mid, (1, 7), pad=(0, 3)),
         _c(192, (7, 1), pad=(3, 0))],
        [_c(mid, 1), _c(mid, (7, 1), pad=(3, 0)),
         _c(mid, (1, 7), pad=(0, 3)), _c(mid, (7, 1), pad=(3, 0)),
         _c(192, (1, 7), pad=(0, 3))],
        ["avgpool", _c(192, 1)],
    ])


def _e_stage(prefix):
    """Expanded tail block: branches 2 and 3 fork into (1x3, 3x1) pairs
    whose outputs concat — encoded as (stem_rows, [fork_rows, ...])."""
    return (prefix, "expand", [
        ([_c(320, 1)], None),
        ([_c(384, 1)], [[_c(384, (1, 3), pad=(0, 1))],
                        [_c(384, (3, 1), pad=(1, 0))]]),
        ([_c(448, 1), _c(384, 3, pad=1)],
         [[_c(384, (1, 3), pad=(0, 1))], [_c(384, (3, 1), pad=(1, 0))]]),
        (["avgpool", _c(192, 1)], None),
    ])


# 35x35 A mixes, the 17x17 reduction + C mixes, the 8x8 reduction + tail
_STAGES = (
    _a_stage("A1_", 32),
    _a_stage("A2_", 64),
    _a_stage("A3_", 64),
    ("B_", [
        [_c(384, 3, stride=2)],
        [_c(64, 1), _c(96, 3, pad=1), _c(96, 3, stride=2)],
        ["maxpool"],
    ]),
    _c_stage("C1_", 128),
    _c_stage("C2_", 160),
    _c_stage("C3_", 160),
    _c_stage("C4_", 192),
    ("D_", [
        [_c(192, 1), _c(320, 3, stride=2)],
        [_c(192, 1), _c(192, (1, 7), pad=(0, 3)),
         _c(192, (7, 1), pad=(3, 0)), _c(192, 3, stride=2)],
        ["maxpool"],
    ]),
    _e_stage("E1_"),
    _e_stage("E2_"),
)


class _Mix(HybridBlock):
    """Concat-on-channels over parallel branches from a table row."""

    def __init__(self, branches, prefix=None):
        super(_Mix, self).__init__(prefix=prefix)
        with self.name_scope():
            for rows in branches:
                self.register_child(_branch(rows))

    def hybrid_forward(self, F, x):
        return F.Concat(*[b(x) for b in self._children.values()], dim=1)


class _ExpandedMix(HybridBlock):
    """Tail mix whose branches may fork: each entry is (stem rows,
    fork branch lists or None); fork outputs concat before the outer
    concat. Children register stem-then-forks per branch, the order the
    parameter-name contract fixes."""

    def __init__(self, spec, prefix=None):
        super(_ExpandedMix, self).__init__(prefix=prefix)
        self._plan = []
        with self.name_scope():
            for rows, forks in spec:
                stem = _branch(rows)
                self.register_child(stem)
                arms = []
                if forks:
                    for fork_rows in forks:
                        arm = _branch(fork_rows)
                        self.register_child(arm)
                        arms.append(arm)
                self._plan.append((stem, arms))

    def hybrid_forward(self, F, x):
        outs = []
        for stem, arms in self._plan:
            y = stem(x)
            if arms:
                y = F.Concat(*[arm(y) for arm in arms], dim=1)
            outs.append(y)
        return F.Concat(*outs, dim=1)


class Inception3(HybridBlock):
    """Inception V3 assembled from the topology tables above
    (reference: inception.py Inception3)."""

    def __init__(self, classes=1000, **kwargs):
        super(Inception3, self).__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            for row in _STEM:
                if row == "maxpool":
                    self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
                else:
                    self.features.add(_cbr(row))
            for stage in _STAGES:
                if stage[1] == "expand":
                    self.features.add(_ExpandedMix(stage[2],
                                                   prefix=stage[0]))
                else:
                    self.features.add(_Mix(stage[1], prefix=stage[0]))
            self.features.add(nn.AvgPool2D(pool_size=8))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def inception_v3(pretrained=False, ctx=None, root="~/.mxnet/models",
                 **kwargs):
    """Reference: inception.py inception_v3."""
    net = Inception3(**kwargs)
    if pretrained:
        net.load_parameters(get_model_file("inceptionv3", root=root),
                            ctx=ctx)
    return net
