"""Fused optimizer-update Pallas kernels (SGD-momentum and Adam).

The forensics boundary report attributes the train step's residual HBM
round-trips to the update tail: XLA fuses the elementwise update math
well enough, but each param's weight / grad / momentum / variance makes
its own trip through HBM per fused-multiply stage. Here the whole
update rule runs as one VMEM-resident kernel per parameter block —
weight and state tiles are loaded once, updated in registers, and
written back in place (the outputs alias the weight/state inputs, so on
TPU the update is a true in-place donation like the surrounding fused
step).

Bitwise contract: the kernel body *is* the optimizer's own pure-lax
``fused_rule`` evaluated on VMEM refs — there is no reimplementation to
drift. Off-TPU the dispatchers run the lax rule directly (the tier-1
path, so tier-1 training numerics are bitwise-unchanged by
construction); ``interpret=True`` forces the Pallas interpreter for
parity tests. Interpret-mode parity is ULP-bounded, not bitwise:
XLA:CPU's FMA-contraction choices depend on operand shape and layout,
and the interpreter's ref plumbing changes them — the tests pin the
kernel to within a few ULPs of the jitted twin.

Hyperparameters arrive as a packed f32 SMEM vector, so LR-schedule
steps change data, not trace constants — zero recompiles across
schedule updates, same weak-type discipline as ``executor`` fused
steps.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import _interpret_default

__all__ = ["sgd_fused_update", "adam_fused_update"]

# kernel-contract registry: exported kernel -> module-level pure-lax
# twin (see tools/check_pallas_contracts.py)
PALLAS_KERNELS = {
    "sgd_fused_update": "_sgd_fused_xla",
    "adam_fused_update": "_adam_fused_xla",
}

_LANES = 128


def _sgd_fused_xla(w, g, state, h):
    """Pure-lax twin: the optimizer's own ``_sgd_fused`` rule."""
    from ...optimizer import _sgd_fused
    return _sgd_fused(w, g, state, h)


def _adam_fused_xla(w, g, state, h):
    """Pure-lax twin: the optimizer's own ``_adam_fused`` rule."""
    from ...optimizer import _adam_fused
    return _adam_fused(w, g, state, h)


def _update_kernel(h_ref, w_ref, g_ref, *refs, rule, n_state,
                   hyper_keys):
    """One row-block of the update: rebuild the hyper dict from SMEM
    scalars (key *presence* — e.g. ``clip_gradient`` — is static via
    ``hyper_keys``; values are data) and evaluate the optimizer's lax
    rule on the VMEM tiles."""
    h = {k: h_ref[i] for i, k in enumerate(hyper_keys)}
    state = tuple(refs[i][:] for i in range(n_state))
    w_new, s_new = rule(w_ref[:], g_ref[:], state, h)
    refs[n_state][:] = w_new
    for i, s in enumerate(s_new):
        refs[n_state + 1 + i][:] = s


@functools.partial(jax.jit, static_argnames=("rule", "hyper_keys",
                                             "block_rows", "interpret"))
def _fused_update(rule, hv, w, g, state, hyper_keys, block_rows,
                  interpret):
    shape, dtype = w.shape, w.dtype
    n = max(1, int(np.prod(shape)))
    rows = -(-n // _LANES)
    rows = -(-rows // 8) * 8      # f32 sublane multiple

    def _flat(x):
        x = x.reshape(-1)
        return jnp.pad(x, (0, rows * _LANES - n)).reshape(rows, _LANES)

    wf, gf = _flat(w), _flat(g)
    sf = tuple(_flat(s) for s in state)
    block_rows = min(block_rows, rows)
    while rows % block_rows:
        block_rows //= 2
    n_state = len(sf)
    bspec = pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0))
    kernel = functools.partial(_update_kernel, rule=rule,
                               n_state=n_state, hyper_keys=hyper_keys)
    out = pl.pallas_call(
        kernel,
        grid=(rows // block_rows,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
                 + [bspec] * (2 + n_state),
        out_specs=[bspec] * (1 + n_state),
        out_shape=[jax.ShapeDtypeStruct((rows, _LANES), dtype)]
                  * (1 + n_state),
        # weight/state tiles update in place (operands: hv=0, w=1,
        # g=2, state=3..)
        input_output_aliases=dict(
            [(1, 0)] + [(3 + i, 1 + i) for i in range(n_state)]),
        compiler_params=getattr(pltpu, "CompilerParams",
                                getattr(pltpu, "TPUCompilerParams",
                                        None))(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(hv, wf, gf, *sf)
    w_new = out[0].reshape(-1)[:n].reshape(shape)
    s_new = tuple(o.reshape(-1)[:n].reshape(shape) for o in out[1:])
    return w_new, s_new


def _fused_update_dispatch(rule, w, g, state, h, block_rows, interpret):
    if interpret is None:
        if _interpret_default(w):
            return rule(w, g, tuple(state), h)
        interpret = False
    hyper_keys = tuple(sorted(h))
    hv = jnp.stack([jnp.asarray(h[k], jnp.float32).reshape(())
                    for k in hyper_keys])
    return _fused_update(rule, hv, w, g, tuple(state), hyper_keys,
                         int(block_rows), bool(interpret))


def sgd_fused_update(w, g, state, h, block_rows=256, interpret=None):
    """SGD(-momentum) update as a single VMEM-resident kernel.

    ``state`` is ``(momentum,)`` or ``()`` (stateless SGD); ``h`` is
    the fused-rule hyper dict (``lr``, ``wd``, ``rescale_grad``,
    optionally ``momentum`` / ``clip_gradient``). Returns
    ``(w_new, state_new)`` exactly like ``optimizer._sgd_fused``, which
    is the bitwise twin and the off-TPU path."""
    return _fused_update_dispatch(_sgd_fused_xla, w, g, state, h,
                                  block_rows, interpret)


def adam_fused_update(w, g, state, h, block_rows=256, interpret=None):
    """Adam update as a single VMEM-resident kernel.

    ``state`` is ``(mean, var)``; ``h`` is the fused-rule hyper dict
    (``lr``, ``wd``, ``beta1``/``one_minus_beta1``,
    ``beta2``/``one_minus_beta2``, ``epsilon``, ``rescale_grad``,
    optionally ``clip_gradient``). Returns ``(w_new, (mean, var))``
    exactly like ``optimizer._adam_fused``, which is the bitwise twin
    and the off-TPU path."""
    return _fused_update_dispatch(_adam_fused_xla, w, g, state, h,
                                  block_rows, interpret)
