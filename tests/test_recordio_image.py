"""RecordIO + image pipeline tests
(reference: tests/python/unittest/test_recordio.py, test_image.py)."""
import os
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio, image
from mxnet_tpu.gluon.data import DataLoader
from mxnet_tpu.gluon.data.dataset import ArrayDataset


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "test.rec")
    w = recordio.MXRecordIO(path, "w")
    records = [b"x" * n for n in (1, 5, 100, 1000)]
    for r in records:
        w.write(r)
    w.close()
    r = recordio.MXRecordIO(path, "r")
    for expect in records:
        assert r.read() == expect
    assert r.read() is None
    r.close()


def test_recordio_native_backend_used():
    from mxnet_tpu import _native
    lib = _native.recordio_lib()
    assert lib is not None, "native recordio library failed to build"


def test_indexed_recordio(tmp_path):
    path = str(tmp_path / "test.rec")
    idx = str(tmp_path / "test.idx")
    w = recordio.MXIndexedRecordIO(idx, path, "w")
    for i in range(10):
        w.write_idx(i, b"record%d" % i)
    w.close()
    r = recordio.MXIndexedRecordIO(idx, path, "r")
    assert r.keys == list(range(10))
    assert r.read_idx(7) == b"record7"
    assert r.read_idx(2) == b"record2"
    r.close()


def test_pack_unpack_label_array():
    header = recordio.IRHeader(0, np.array([1.0, 2.0, 3.0]), 7, 0)
    s = recordio.pack(header, b"payload")
    h2, payload = recordio.unpack(s)
    assert payload == b"payload"
    assert h2.id == 7
    assert np.allclose(h2.label, [1.0, 2.0, 3.0])


def test_pack_unpack_scalar_label():
    s = recordio.pack((0, 3.0, 1, 0), b"data")
    h, payload = recordio.unpack(s)
    assert h.label == 3.0
    assert payload == b"data"


def test_pack_img_unpack_img():
    img = (np.random.rand(32, 32, 3) * 255).astype(np.uint8)
    s = recordio.pack_img((0, 1.0, 0, 0), img, quality=100, img_fmt=".png")
    header, decoded = recordio.unpack_img(s)
    assert header.label == 1.0
    assert decoded.shape == (32, 32, 3)
    # png is lossless: exact round trip (RGB order preserved)
    assert np.array_equal(decoded.asnumpy(), img)


def test_image_resize_crop():
    img = mx.nd.array((np.random.rand(40, 60, 3) * 255).astype(np.uint8),
                      dtype="uint8")
    out = image.imresize(img, 30, 20)
    assert out.shape == (20, 30, 3)
    short = image.resize_short(img, 20)
    assert min(short.shape[:2]) == 20
    crop, rect = image.center_crop(img, (20, 20))
    assert crop.shape == (20, 20, 3)
    rnd, rect = image.random_crop(img, (16, 16))
    assert rnd.shape == (16, 16, 3)


def test_augmenter_list():
    augs = image.CreateAugmenter((3, 24, 24), resize=26, rand_mirror=True,
                                 mean=True, std=True)
    img = mx.nd.array((np.random.rand(40, 60, 3) * 255).astype(np.uint8),
                      dtype="uint8")
    for aug in augs:
        img = aug(img)
    assert img.shape == (24, 24, 3)
    assert img.dtype == np.float32


def test_image_iter_from_rec(tmp_path):
    # build a small rec pack
    path = str(tmp_path / "imgs.rec")
    idx = str(tmp_path / "imgs.idx")
    w = recordio.MXIndexedRecordIO(idx, path, "w")
    for i in range(8):
        img = (np.random.rand(32, 32, 3) * 255).astype(np.uint8)
        w.write_idx(i, recordio.pack_img((0, float(i % 2), i, 0), img))
    w.close()
    it = image.ImageIter(batch_size=4, data_shape=(3, 28, 28),
                         path_imgrec=path, rand_crop=True, rand_mirror=True)
    batch = next(it)
    assert batch.data[0].shape == (4, 3, 28, 28)
    assert batch.label[0].shape == (4,)
    n = 1 + sum(1 for _ in it)
    assert n == 2


def test_dataloader_with_workers():
    X = np.random.rand(32, 4).astype(np.float32)
    y = np.arange(32, dtype=np.float32)
    ds = ArrayDataset(X, y)
    loader = DataLoader(ds, batch_size=8, shuffle=False, num_workers=2)
    seen = 0
    for data, label in loader:
        assert data.shape == (8, 4)
        np.testing.assert_allclose(label.asnumpy(),
                                   y[seen:seen + 8])
        seen += 8
    assert seen == 32


def test_record_file_dataset(tmp_path):
    path = str(tmp_path / "ds.rec")
    idx = str(tmp_path / "ds.idx")
    w = recordio.MXIndexedRecordIO(idx, path, "w")
    for i in range(5):
        w.write_idx(i, b"item%d" % i)
    w.close()
    from mxnet_tpu.gluon.data.dataset import RecordFileDataset
    ds = RecordFileDataset(path)
    assert len(ds) == 5
    assert ds[3] == b"item3"


# ---------------------------------------------------------------------------
# native parallel decode pool (src/native/imagedec.cc; reference hot
# path src/io/iter_image_recordio_2.cc ParseChunk)
# ---------------------------------------------------------------------------

def _jpegs(n, hw=96, seed=0):
    cv2 = pytest.importorskip("cv2")
    rng = np.random.RandomState(seed)
    bufs = []
    for i in range(n):
        im = rng.randint(0, 255, (hw + i, hw + 8 - i, 3), dtype=np.uint8)
        ok, b = cv2.imencode(".jpg", im)
        bufs.append(b.tobytes())
    return bufs


def _native_dec(*a, **k):
    try:
        return image.NativeImageDecoder(*a, **k)
    except mx.MXNetError:
        pytest.skip("native decoder unavailable (no g++/OpenCV)")


def test_native_decode_matches_python_exact():
    """Decode + center crop only (no resize): bit-exact vs the Python
    cv2 path — both run the same libjpeg decode."""
    bufs = _jpegs(6)
    dec = _native_dec((3, 64, 64))
    out = dec.decode_batch(bufs)
    for i, b in enumerate(bufs):
        img = image.imdecode(b, to_ndarray=False)
        ref = image.center_crop(img, (64, 64))[0]
        np.testing.assert_array_equal(
            out[i], np.asarray(ref).transpose(2, 0, 1).astype(np.float32))


def test_native_decode_resize_close_to_python():
    """With resize the system OpenCV (4.x) and pip cv2 (5.x) differ by
    INTER_CUBIC rounding only — bounded by ~2 uint8 ULP."""
    bufs = _jpegs(6, hw=128)
    mean = np.array([123.68, 116.28, 103.53], np.float32)
    std = np.array([58.395, 57.12, 57.375], np.float32)
    dec = _native_dec((3, 96, 96), resize=112, mean=mean, std=std)
    out = dec.decode_batch(bufs)
    augs = image.CreateAugmenter((3, 96, 96), resize=112, mean=mean, std=std)
    for i, b in enumerate(bufs):
        img = image.imdecode(b, to_ndarray=False)
        for a in augs:
            img = a(img)
        ref = np.asarray(img).transpose(2, 0, 1)
        assert np.abs(out[i] - ref).max() < 2.5 / 57.0

def test_native_decode_thread_invariant_and_stream_keyed():
    """Random crop/mirror draws are keyed per (seed, stream position):
    identical for any thread count, different at different positions."""
    bufs = _jpegs(12, hw=128)
    kw = dict(resize=112, rand_crop=True, rand_mirror=True)
    d1 = _native_dec((3, 96, 96), num_threads=1, seed=5, **kw)
    d3 = _native_dec((3, 96, 96), num_threads=3, seed=5, **kw)
    a = d1.decode_batch(bufs, base=40)
    b = d3.decode_batch(bufs, base=40)
    np.testing.assert_array_equal(a, b)
    c = d3.decode_batch(bufs, base=52)
    assert not np.array_equal(a, c)
    # many consecutive batches through one pool: no cross-batch races
    for k in range(16):
        d3.decode_batch(bufs, base=k)


def test_native_decode_corrupt_buffer_raises():
    dec = _native_dec((3, 32, 32))
    with pytest.raises(mx.MXNetError, match="decode"):
        dec.decode_batch([b"\xff\xd8 not a real jpeg"])


def test_image_record_iter_native_path(tmp_path):
    """ImageRecordIter(preprocess_threads=N) engages the pool and yields
    the same labels/shapes as the Python path; unsupported augmentations
    fall back to the Python loop."""
    cv2 = pytest.importorskip("cv2")
    rng = np.random.RandomState(3)
    rec_path = str(tmp_path / "t.rec")
    idx_path = str(tmp_path / "t.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(10):
        im = rng.randint(0, 255, (80, 80, 3), dtype=np.uint8)
        ok, b = cv2.imencode(".jpg", im)
        w.write_idx(i, recordio.pack(recordio.IRHeader(0, float(i), i, 0),
                                     b.tobytes()))
    w.close()
    from mxnet_tpu.io import ImageRecordIter
    it = ImageRecordIter(path_imgrec=rec_path, data_shape=(3, 64, 64),
                         batch_size=4, preprocess_threads=2)
    if it._native is None:
        pytest.skip("native decoder unavailable")
    ref = ImageRecordIter(path_imgrec=rec_path, data_shape=(3, 64, 64),
                          batch_size=4)
    assert ref._native is None
    b_nat, b_ref = next(it), next(ref)
    np.testing.assert_array_equal(b_nat.label[0].asnumpy(),
                                  b_ref.label[0].asnumpy())
    np.testing.assert_allclose(b_nat.data[0].asnumpy(),
                               b_ref.data[0].asnumpy(), atol=1e-5)
    # partial final batch pads identically
    for _ in range(1):
        next(it), next(ref)
    b_nat, b_ref = next(it), next(ref)
    assert b_nat.pad == b_ref.pad == 2
    # color jitter is not in the native fast path -> Python fallback
    it2 = ImageRecordIter(path_imgrec=rec_path, data_shape=(3, 64, 64),
                          batch_size=4, preprocess_threads=2, brightness=0.2)
    assert it2._native is None
