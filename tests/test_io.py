"""Data iterator tests (reference: tests/python/unittest/test_io.py)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io


def test_ndarrayiter_basic():
    data = np.arange(100).reshape(25, 4).astype(np.float32)
    label = np.arange(25).astype(np.float32)
    it = io.NDArrayIter(data, label, batch_size=5)
    batches = list(it)
    assert len(batches) == 5
    assert batches[0].data[0].shape == (5, 4)
    assert batches[0].label[0].shape == (5,)
    np.testing.assert_allclose(batches[1].data[0].asnumpy(), data[5:10])
    # second epoch after reset
    it.reset()
    assert len(list(it)) == 5


def test_ndarrayiter_pad():
    data = np.arange(23 * 2).reshape(23, 2).astype(np.float32)
    it = io.NDArrayIter(data, batch_size=5, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 5
    assert batches[-1].pad == 2
    assert batches[-1].data[0].shape == (5, 2)
    # padded tail wraps to the start
    np.testing.assert_allclose(batches[-1].data[0].asnumpy()[3:], data[:2])


def test_ndarrayiter_discard():
    data = np.zeros((23, 2), dtype=np.float32)
    it = io.NDArrayIter(data, batch_size=5, last_batch_handle="discard")
    assert len(list(it)) == 4


def test_ndarrayiter_shuffle_keeps_pairing():
    data = np.arange(40).astype(np.float32).reshape(40, 1)
    label = np.arange(40).astype(np.float32)
    it = io.NDArrayIter(data, label, batch_size=8, shuffle=True)
    for batch in it:
        np.testing.assert_allclose(batch.data[0].asnumpy()[:, 0],
                                   batch.label[0].asnumpy())


def test_ndarrayiter_dict_input():
    it = io.NDArrayIter({"a": np.zeros((10, 2)), "b": np.zeros((10, 3))},
                        batch_size=5)
    names = [d.name for d in it.provide_data]
    assert sorted(names) == ["a", "b"]
    b = next(it)
    assert len(b.data) == 2


def test_provide_data_desc():
    data = np.zeros((10, 3, 4, 4), dtype=np.float32)
    it = io.NDArrayIter(data, batch_size=2)
    desc = it.provide_data[0]
    assert desc.name == "data"
    assert desc.shape == (2, 3, 4, 4)
    assert io.DataDesc.get_batch_axis("NCHW") == 0


def test_resize_iter():
    data = np.zeros((20, 2), dtype=np.float32)
    base = io.NDArrayIter(data, batch_size=5)
    it = io.ResizeIter(base, 7)
    assert len(list(it)) == 7
    it.reset()
    assert len(list(it)) == 7


def test_prefetching_iter():
    data = np.arange(60).reshape(20, 3).astype(np.float32)
    base = io.NDArrayIter(data, batch_size=4)
    it = io.PrefetchingIter(base)
    batches = list(it)
    assert len(batches) == 5
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:4])
    it.reset()
    assert len(list(it)) == 5


def test_csv_iter(tmp_path):
    data = np.random.rand(12, 3).astype(np.float32)
    f = tmp_path / "d.csv"
    np.savetxt(f, data, delimiter=",")
    it = io.CSVIter(data_csv=str(f), data_shape=(3,), batch_size=4)
    batches = list(it)
    assert len(batches) == 3
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:4],
                               rtol=1e-5)


def test_ndarrayiter_roll_over_multi_epoch():
    """roll_over with labels must survive multiple epochs (the cache is
    consumed by both getdata and getlabel)."""
    data = np.arange(10).astype(np.float32).reshape(10, 1)
    label = np.arange(10).astype(np.float32)
    it = io.NDArrayIter(data, label, batch_size=4,
                        last_batch_handle="roll_over")
    for _epoch in range(3):
        total = 0
        for batch in it:
            assert batch.data[0].shape == (4, 1)
            np.testing.assert_allclose(batch.data[0].asnumpy()[:, 0],
                                       batch.label[0].asnumpy())
            total += 4
        it.reset()
        assert total >= 8


# ---------------------------------------------------------------------------
# PR 6: sharding contract, seeded shuffles, async pipeline, resumable cursor
# ---------------------------------------------------------------------------

import mxnet_tpu.checkpoint as ckpt
from mxnet_tpu import fault
from mxnet_tpu.base import MXNetError


@pytest.fixture(autouse=True)
def _clean_io_faults():
    fault.disarm()
    yield
    fault.disarm()


def test_shard_bounds_partition_contract():
    """Parts are disjoint, exhaustive, and balanced to within one
    sample, for every (n, num_parts) shape including tails."""
    for n in (0, 1, 7, 40, 41, 99):
        for parts in (1, 2, 3, 7, 11):
            seen = []
            sizes = []
            for p in range(parts):
                lo, hi = io.shard_bounds(n, parts, p)
                seen.extend(range(lo, hi))
                sizes.append(hi - lo)
            assert seen == list(range(n)), (n, parts)
            assert max(sizes) - min(sizes) <= 1, (n, parts)
    with pytest.raises(MXNetError):
        io.shard_bounds(10, 3, 3)
    with pytest.raises(MXNetError):
        io.shard_bounds(10, 0, 0)


def test_indexed_recordio_shard_keys_partition(tmp_path):
    """MXIndexedRecordIO.shard_keys follows the shared partition
    contract: concatenating the shards reproduces the key sequence
    (disjoint + exhaustive + ordered), sizes balanced to within one —
    including non-contiguous keys."""
    from mxnet_tpu import recordio
    rec, idx = str(tmp_path / "a.rec"), str(tmp_path / "a.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(11):
        w.write_idx(i * 3, recordio.pack(
            recordio.IRHeader(0, float(i), i * 3, 0), b"x%d" % i))
    w.close()
    r = recordio.MXIndexedRecordIO(idx, rec, "r")
    parts = [r.shard_keys(4, p) for p in range(4)]
    assert [k for part in parts for k in part] == list(r.keys)
    assert max(len(p) for p in parts) - min(len(p) for p in parts) <= 1
    # every sharded key is readable
    h, _ = recordio.unpack(r.read_idx(parts[3][0]))
    assert h.id == parts[3][0]
    r.close()


def test_ndarrayiter_sharding_disjoint_exhaustive():
    data = np.arange(23).astype(np.float32).reshape(23, 1)
    got = []
    for p in range(3):
        it = io.NDArrayIter(data, batch_size=2, num_parts=3, part_index=p,
                            last_batch_handle="discard")
        for b in it:
            got.extend(b.data[0].asnumpy()[:, 0].tolist())
    # discard drops at most batch_size-1 per part; everything kept is
    # unique and the parts cover distinct ranges
    assert len(got) == len(set(got))
    assert len(got) >= 23 - 3 * 1


def test_ndarrayiter_seed_private_and_deterministic():
    """Seeded epoch shuffles replay exactly and never consume the
    global NumPy stream."""
    data = np.arange(40).astype(np.float32).reshape(40, 1)

    def stream(seed, epochs=3):
        it = io.NDArrayIter(data, batch_size=8, shuffle=True, seed=seed)
        out = []
        for _ in range(epochs):
            out.append(np.concatenate(
                [b.data[0].asnumpy()[:, 0] for b in it]))
            it.reset()
        return out

    np.random.seed(123)
    before = np.random.random_sample(4)
    np.random.seed(123)
    a = stream(5)
    after = np.random.random_sample(4)
    np.testing.assert_array_equal(before, after)   # global RNG untouched
    b = stream(5)
    for ea, eb in zip(a, b):
        np.testing.assert_array_equal(ea, eb)
    # different epochs permute differently
    assert not np.array_equal(a[0], a[1])


def test_ndarrayiter_cursor_seek_bitwise():
    """restore_state seeks a fresh iterator (even one built with a
    DIFFERENT seed) to the cursor and replays the interrupted stream
    bit-for-bit — the seed travels with the cursor."""
    data = np.arange(40).astype(np.float32).reshape(40, 1)
    it = io.NDArrayIter(data, batch_size=8, shuffle=True, seed=5)
    full = []
    for epoch in range(3):
        full.append([b.data[0].asnumpy().copy() for b in it])
        it.reset()

    it2 = io.NDArrayIter(data, batch_size=8, shuffle=True, seed=999)
    it2.restore_state({"kind": "NDArrayIter", "epoch": 1, "batch": 2,
                       "seed": 5, "shuffle": True, "num_data": 40})
    rest = [b.data[0].asnumpy().copy() for b in it2]
    ref = full[1][2:]
    assert len(rest) == len(ref)
    for a, b in zip(ref, rest):
        np.testing.assert_array_equal(a, b)
    # a cursor from a different stream is refused: wrong size, wrong
    # batching, wrong shuffling, or a cursor of another iterator kind
    with pytest.raises(MXNetError):
        it2.restore_state({"epoch": 0, "batch": 0, "num_data": 39})
    with pytest.raises(MXNetError):
        it2.restore_state({"epoch": 0, "batch": 0, "batch_size": 4})
    with pytest.raises(MXNetError):
        it2.restore_state({"epoch": 0, "batch": 0, "shuffle": False})
    with pytest.raises(MXNetError):
        it2.restore_state({"kind": "DataPipeline", "epoch": 0,
                           "batch": 0})
    # roll_over carries cross-epoch state: no cursor, seek refused
    it3 = io.NDArrayIter(data, batch_size=8,
                         last_batch_handle="roll_over")
    assert it3.checkpoint_state(0, 0) is None
    with pytest.raises(MXNetError):
        it3.restore_state({"epoch": 0, "batch": 0})


def test_resize_iter_empty_after_reset_raises_clearly():
    class _EmptyIter(io.DataIter):
        def __init__(self):
            super().__init__(2)
            self.provide_data = [io.DataDesc("data", (2, 2))]
            self.provide_label = []

        def iter_next(self):
            return False

        def next(self):
            raise StopIteration

    it = io.ResizeIter(_EmptyIter(), 3)
    with pytest.raises(MXNetError, match="no batches after"):
        list(it)


def test_prefetching_iter_close_is_restartable():
    data = np.arange(60).reshape(20, 3).astype(np.float32)
    base = io.NDArrayIter(data, batch_size=4)
    with io.PrefetchingIter(base) as it:
        first = next(it)
        np.testing.assert_allclose(first.data[0].asnumpy(), data[:4])
        it.close()                      # idempotent with __exit__
        assert not it.started
        # a closed iterator respawns its threads on the next use
        second = next(it)
        np.testing.assert_allclose(second.data[0].asnumpy(), data[4:8])
    assert not it.started
    it.reset()
    assert len(list(it)) == 5


def _double_augment(data_list, rng):
    """Module-level so pipeline workers can pickle it; uses the
    (seed, epoch, index)-keyed rng for a deterministic jitter."""
    noise = rng.normal(size=data_list[0].shape).astype(np.float32)
    return [data_list[0] * 2.0 + noise] + list(data_list[1:])


def _pipe_stream(workers, seed=7, epochs=2, augment=None, shuffle=True,
                 **kw):
    data = np.arange(200, dtype=np.float32).reshape(50, 4)
    label = np.arange(50, dtype=np.float32)
    src = io.ArrayBatchSource(data, label, batch_size=8, shuffle=shuffle,
                              seed=seed, augment_fn=augment, **kw)
    out = []
    with io.DataPipeline(src, num_workers=workers, prefetch=2) as p:
        for _ in range(epochs):
            for b in p:
                out.append((b.data[0].asnumpy().copy(),
                            b.label[0].asnumpy().copy(), b.pad))
            p.reset()
    return out


def test_pipeline_multiworker_bitwise_equality():
    """THE pipeline determinism claim: the multi-worker stream —
    including seeded shuffles and per-batch augmentation RNG — is
    bitwise-identical to the inline (workers=0) stream."""
    inline = _pipe_stream(0, augment=_double_augment)
    pooled = _pipe_stream(2, augment=_double_augment)
    assert len(inline) == len(pooled) == 14
    for (d0, l0, p0), (d2, l2, p2) in zip(inline, pooled):
        assert p0 == p2
        np.testing.assert_array_equal(d0, d2)
        np.testing.assert_array_equal(l0, l2)


def test_pipeline_shards_cover_stream():
    parts = [_pipe_stream(0, shuffle=False, epochs=1, num_parts=3,
                          part_index=p, last_batch_handle="discard",
                          seed=0) for p in range(3)]
    seen = [x for part in parts for (_d, l, _p) in part for x in l]
    assert len(seen) == len(set(seen))           # disjoint
    assert len(seen) >= 50 - 3 * 7               # exhaustive minus tails


def test_pipeline_cursor_kill_resume_bitwise():
    """Kill-at-batch-N drill at the iterator level: a fresh pipeline
    (different seed) seeked to the cursor reproduces the uninterrupted
    stream exactly, across the epoch boundary."""
    full = _pipe_stream(0, seed=7, epochs=2)

    data = np.arange(200, dtype=np.float32).reshape(50, 4)
    label = np.arange(50, dtype=np.float32)
    src = io.ArrayBatchSource(data, label, batch_size=8, shuffle=True,
                              seed=7)
    p1 = io.DataPipeline(src, num_workers=0)
    for _ in range(3):
        p1.next()
    cur = p1.checkpoint_state(0, 3)
    p1.close()                                    # "the process dies"

    src2 = io.ArrayBatchSource(data, label, batch_size=8, shuffle=True,
                               seed=31337)
    p2 = io.DataPipeline(src2, num_workers=2)
    p2.restore_state(cur)
    rest = []
    for _ in range(2):
        for b in p2:
            rest.append((b.data[0].asnumpy().copy(),
                         b.label[0].asnumpy().copy(), b.pad))
        p2.reset()
    p2.close()
    ref = full[3:]
    assert len(rest) == len(ref)
    for (da, la, pa), (db, lb, pb) in zip(ref, rest):
        assert pa == pb
        np.testing.assert_array_equal(da, db)
        np.testing.assert_array_equal(la, lb)
    # stream-identity check: cursor over different data size refused
    src3 = io.ArrayBatchSource(data[:40], label[:40], batch_size=8)
    p3 = io.DataPipeline(src3, num_workers=0)
    with pytest.raises(MXNetError):
        p3.restore_state(cur)
    p3.close()


@pytest.mark.slow
def test_pipeline_worker_crash_restarts_without_loss():
    """An io.worker crash (SIGKILL-grade os._exit in the decode
    process) restarts the pool in place; the consumer sees no lost, no
    duplicated, and no reordered batch."""
    from mxnet_tpu import telemetry as tm
    inline = _pipe_stream(0, seed=3, epochs=1)

    def val(): 
        fam = tm.REGISTRY._families.get("io/worker_restarts_total")
        return fam.value if fam is not None else 0

    before = val()
    fault.arm("io.worker", step=3, kind="crash")
    try:
        crashed = _pipe_stream(2, seed=3, epochs=1)
    finally:
        fault.disarm()
    assert len(crashed) == len(inline) == 7
    for (d0, l0, p0), (d2, l2, p2) in zip(inline, crashed):
        assert p0 == p2
        np.testing.assert_array_equal(d0, d2)
        np.testing.assert_array_equal(l0, l2)
    assert val() > before


def test_pipeline_worker_restart_budget_enforced():
    data = np.arange(200, dtype=np.float32).reshape(50, 4)
    src = io.ArrayBatchSource(data, batch_size=8)
    fault.arm("io.worker", step=1, kind="crash", count=99)
    p = io.DataPipeline(src, num_workers=1, restart_budget=1)
    try:
        with pytest.raises(MXNetError, match="restart budget"):
            list(p)
        # giving up reclaims what the dead workers staged: nothing of
        # this pipeline's shm namespace survives in /dev/shm
        leaked = [f for f in os.listdir("/dev/shm")
                  if f.startswith(p._shm_prefix)] \
            if os.path.isdir("/dev/shm") else []
        assert leaked == []
    finally:
        fault.disarm()
        p.close()
