"""Gluon losses.

Reference: python/mxnet/gluon/loss.py (708 LoC, 21 losses). Same API:
every loss is a HybridBlock taking (pred, label[, sample_weight]) and
returning a per-sample loss averaged over all but the batch axis.
"""
from __future__ import annotations

from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "CTCLoss", "HuberLoss", "HingeLoss",
           "SquaredHingeLoss", "LogisticLoss", "TripletLoss",
           "PoissonNLLLoss", "CosineEmbeddingLoss"]


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    """Reference: gluon/loss.py _apply_weighting."""
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight)
    if weight is not None:
        loss = loss * weight
    return loss


def _reshape_like(F, x, y):
    return x.reshape(y.shape)


class Loss(HybridBlock):
    """Base loss (reference: gluon/loss.py Loss)."""

    def __init__(self, weight, batch_axis, **kwargs):
        super(Loss, self).__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return "%s(batch_axis=%s, w=%s)" % (
            self.__class__.__name__, self._batch_axis, self._weight)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    def _mean_nonbatch(self, F, loss):
        axes = [i for i in range(loss.ndim) if i != self._batch_axis]
        if not axes:
            return loss
        return F.mean(loss, axis=tuple(axes))


class L2Loss(Loss):
    r"""0.5*(pred-label)^2 (reference: loss.py L2Loss)."""

    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super(L2Loss, self).__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        loss = F.square(_reshape_like(F, label, pred) - pred)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return self._mean_nonbatch(F, loss)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super(L1Loss, self).__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        loss = F.abs(_reshape_like(F, label, pred) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_nonbatch(F, loss)


class SigmoidBinaryCrossEntropyLoss(Loss):
    r"""BCE with optional logits input (reference: loss.py
    SigmoidBinaryCrossEntropyLoss). from_sigmoid=False uses the
    numerically-stable log-sum-exp form."""

    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0,
                 **kwargs):
        super(SigmoidBinaryCrossEntropyLoss, self).__init__(
            weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None,
                       pos_weight=None):
        label = _reshape_like(F, label, pred)
        if not self._from_sigmoid:
            if pos_weight is None:
                loss = F.relu(pred) - pred * label + \
                    F.Activation(-F.abs(pred), act_type="softrelu")
            else:
                log_weight = 1 + F.broadcast_mul(pos_weight - 1, label)
                loss = pred - pred * label + log_weight * \
                    (F.Activation(-F.abs(pred), act_type="softrelu")
                     + F.relu(-pred))
        else:
            eps = 1e-12
            if pos_weight is None:
                loss = -(F.log(pred + eps) * label
                         + F.log(1. - pred + eps) * (1. - label))
            else:
                loss = -(F.broadcast_mul(F.log(pred + eps) * label, pos_weight)
                         + F.log(1. - pred + eps) * (1. - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_nonbatch(F, loss)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    r"""Softmax + CE fused (reference: loss.py SoftmaxCrossEntropyLoss)."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super(SoftmaxCrossEntropyLoss, self).__init__(
            weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
        else:
            label = _reshape_like(F, label, pred)
            loss = -F.sum(pred * label, axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_nonbatch(F, loss)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super(KLDivLoss, self).__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_nonbatch(F, loss)


class CTCLoss(Loss):
    """Connectionist temporal classification loss
    (reference: loss.py CTCLoss; op src/operator/contrib/ctc_loss.cc)."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None,
                 **kwargs):
        assert layout in ("NTC", "TNC")
        assert label_layout in ("NT", "TN")
        self._layout = layout
        self._label_layout = label_layout
        batch_axis = label_layout.find("N")
        super(CTCLoss, self).__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        if self._layout == "NTC":
            pred = pred.swapaxes(0, 1)
        if self._batch_axis == 1:
            label = label.swapaxes(0, 1)
        kwargs = {}
        if pred_lengths is not None:
            kwargs["data_lengths"] = pred_lengths
            kwargs["use_data_lengths"] = True
        if label_lengths is not None:
            kwargs["label_lengths"] = label_lengths
            kwargs["use_label_lengths"] = True
        loss = F.CTCLoss(pred, label, **kwargs)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class HuberLoss(Loss):
    def __init__(self, rho=1, weight=None, batch_axis=0, **kwargs):
        super(HuberLoss, self).__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        loss = F.abs(_reshape_like(F, label, pred) - pred)
        loss = F.where(loss > self._rho,
                       loss - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(loss))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_nonbatch(F, loss)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super(HingeLoss, self).__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.relu(self._margin - pred * label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_nonbatch(F, loss)


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super(SquaredHingeLoss, self).__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(F.relu(self._margin - pred * label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_nonbatch(F, loss)


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super(LogisticLoss, self).__init__(weight, batch_axis, **kwargs)
        if label_format not in ("signed", "binary"):
            raise ValueError("label_format must be signed or binary")
        self._label_format = label_format

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = F.relu(pred) - pred * label + \
            F.Activation(-F.abs(pred), act_type="softrelu")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_nonbatch(F, loss)


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super(TripletLoss, self).__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative,
                       sample_weight=None):
        positive = _reshape_like(F, positive, pred)
        negative = _reshape_like(F, negative, pred)
        axes = tuple(i for i in range(pred.ndim) if i != self._batch_axis)
        loss = F.sum(F.square(pred - positive) - F.square(pred - negative),
                     axis=axes)
        loss = F.relu(loss + self._margin)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class PoissonNLLLoss(Loss):
    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super(PoissonNLLLoss, self).__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def hybrid_forward(self, F, pred, target, sample_weight=None,
                       epsilon=1e-08):
        target = _reshape_like(F, target, pred)
        if self._from_logits:
            loss = F.exp(pred) - target * pred
        else:
            loss = pred - target * F.log(pred + epsilon)
        if self._compute_full:
            import math
            stirling = target * F.log(target + epsilon) - target \
                + 0.5 * F.log(2 * math.pi * (target + epsilon))
            stirling = stirling * (target > 1)
            loss = loss + stirling
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss)


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super(CosineEmbeddingLoss, self).__init__(weight, batch_axis,
                                                  **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        input1 = _reshape_like(F, input1, input2)
        cos = self._cosine_similarity(F, input1, input2)
        label = label.reshape((-1, 1))
        loss = F.where(label == 1, 1 - cos,
                       F.relu(cos - self._margin))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return loss.reshape((-1,))

    def _cosine_similarity(self, F, x, y, axis=-1):
        x_norm = F.norm(x, axis=axis).reshape((-1, 1))
        y_norm = F.norm(y, axis=axis).reshape((-1, 1))
        xy = F.sum(x * y, axis=axis).reshape((-1, 1))
        return xy / F.broadcast_maximum(
            x_norm * y_norm, F.ones_like(x_norm) * 1e-12)
