"""Optimizer classes driving the fused update operators.

Reference: python/mxnet/optimizer.py:444-1498 (17 optimizers, registry,
Updater for kvstore-side application). The update math lives in
mxnet_tpu/ops/optimizer_ops.py as single fused XLA kernels (the analog of
src/operator/optimizer_op.cc, where "update IS an operator" so the whole
step is one engine op); these classes own the bookkeeping: lr/wd
schedules, per-param multipliers, update counts, state creation, and
multi-precision (bf16/fp16 weights with fp32 master copies).
"""
from __future__ import annotations

import logging
import pickle

import numpy

from .base import MXNetError
from .ndarray.ndarray import NDArray, zeros
from .ndarray import register as _register_mod  # noqa: F401  (op funcs)
from . import ndarray as nd

__all__ = ["Optimizer", "SGD", "Signum", "NAG", "Adam", "AdaGrad", "RMSProp",
           "AdaDelta", "Ftrl", "FTML", "Adamax", "Nadam", "SGLD", "DCASGD",
           "Test", "Updater", "get_updater", "create", "register",
           "fused_apply", "fused_state_arrays"]


# ---------------------------------------------------------------------------
# fused functional update rules
#
# Each rule is a PURE function ``rule(weight, grad, state, hyper) ->
# (new_weight, new_state)`` over raw jax arrays: ``state`` is a tuple of
# state arrays (possibly empty), ``hyper`` a dict of python scalars that
# jit traces as weak-typed 0-d arguments — so a changing learning-rate
# schedule (or rescale_grad per batch size) NEVER retriggers XLA
# compilation. The rules mirror the fused kernels in ops/optimizer_ops.py
# op for op, and every scalar-scalar expression the kernels fold in python
# (e.g. Adam's ``1 - beta1``) is folded HOST-side into ``hyper`` here, so
# a fused train step is bitwise-identical to the unfused
# forward/vjp/per-param-kernel sequence (asserted by
# tests/test_fused_step.py).
# ---------------------------------------------------------------------------

def _rule_prep(g, h):
    """grad * rescale_grad (+ optional clip) — mirrors optimizer_ops
    ``_prep_grad``. Clip PRESENCE is static (pytree structure); its value
    is traced."""
    import jax.numpy as jnp
    g = g * h["rescale_grad"]
    if "clip_gradient" in h:
        g = jnp.clip(g, -h["clip_gradient"], h["clip_gradient"])
    return g


def _sgd_fused(w, g, state, h):
    g = _rule_prep(g, h)
    if state:
        mom = h["momentum"] * state[0] - h["lr"] * (g + h["wd"] * w)
        return w + mom, (mom,)
    return w - h["lr"] * (g + h["wd"] * w), ()


def _nag_fused(w, g, state, h):
    if state:
        g = _rule_prep(g, h) + h["wd"] * w
        mom = h["momentum"] * state[0] + g
        return w - h["lr"] * (g + h["momentum"] * mom), (mom,)
    g = _rule_prep(g, h)
    return w - h["lr"] * (g + h["wd"] * w), ()


def _signum_fused(w, g, state, h):
    import jax.numpy as jnp
    g = _rule_prep(g, h)
    if state:
        mom = h["momentum"] * state[0] - h["one_minus_momentum"] * g
        wn = (h["wdlh_coef"] * w + h["lr"] * jnp.sign(mom)
              - h["lr_wd"] * w)
        return wn, (mom,)
    return w - h["lr"] * (jnp.sign(g) + h["wd"] * w), ()


def _adam_fused(w, g, state, h):
    import jax.numpy as jnp
    g = _rule_prep(g, h) + h["wd"] * w
    mean, var = state
    mean_new = h["beta1"] * mean + h["one_minus_beta1"] * g
    var_new = h["beta2"] * var + h["one_minus_beta2"] * jnp.square(g)
    return (w - h["lr"] * mean_new / (jnp.sqrt(var_new) + h["epsilon"]),
            (mean_new, var_new))


def _sgd_fused_pallas(w, g, state, h):
    """:func:`_sgd_fused` as a single VMEM-resident Pallas kernel
    (ops/pallas/fused_update.py) — the weight/state tiles make one HBM
    round-trip instead of one per fused-multiply stage. Off-TPU the
    kernel dispatcher runs ``_sgd_fused`` itself, so this rule IS the
    lax rule everywhere tier-1 runs; on TPU the kernel body evaluates
    the same rule on VMEM refs (bitwise by construction)."""
    from .ops.pallas.fused_update import sgd_fused_update
    return sgd_fused_update(w, g, state, h)


def _adam_fused_pallas(w, g, state, h):
    """:func:`_adam_fused` as a single VMEM-resident Pallas kernel —
    see :func:`_sgd_fused_pallas` for the contract."""
    from .ops.pallas.fused_update import adam_fused_update
    return adam_fused_update(w, g, state, h)


def _adagrad_fused(w, g, state, h):
    import jax.numpy as jnp
    g = _rule_prep(g, h)
    hist = state[0] + g * g
    div = g / (jnp.sqrt(hist) + h["eps"])
    return w - h["lr"] * (div + w * h["wd"]), (hist,)


def _rmsprop_fused(w, g, state, h):
    import jax.numpy as jnp
    g = _rule_prep(g, h) + h["wd"] * w
    if len(state) == 1:                       # plain (Tieleman)
        n_new = h["gamma1"] * state[0] + h["one_minus_gamma1"] * jnp.square(g)
        wn = w - h["lr"] * g / jnp.sqrt(n_new + h["epsilon"])
        if "clip_weights" in h:
            wn = jnp.clip(wn, -h["clip_weights"], h["clip_weights"])
        return wn, (n_new,)
    n, g_acc, delta = state                   # centered (Graves)
    n_new = h["gamma1"] * n + h["one_minus_gamma1"] * jnp.square(g)
    g_acc_new = h["gamma1"] * g_acc + h["one_minus_gamma1"] * g
    delta_new = h["gamma2"] * delta - h["lr"] * g / jnp.sqrt(
        n_new - jnp.square(g_acc_new) + h["epsilon"])
    wn = w + delta_new
    if "clip_weights" in h:
        wn = jnp.clip(wn, -h["clip_weights"], h["clip_weights"])
    return wn, (n_new, g_acc_new, delta_new)


def _adadelta_fused(w, g, state, h):
    import jax.numpy as jnp
    g = _rule_prep(g, h)
    acc_g, acc_delta = state
    acc_g_new = h["rho"] * acc_g + h["one_minus_rho"] * g * g
    cd = (jnp.sqrt(acc_delta + h["epsilon"])
          / jnp.sqrt(acc_g_new + h["epsilon"])) * g
    acc_delta_new = h["rho"] * acc_delta + h["one_minus_rho"] * cd * cd
    return w - cd - h["wd"] * w, (acc_g_new, acc_delta_new)


def _ftrl_fused(w, g, state, h):
    import jax.numpy as jnp
    g = _rule_prep(g, h)
    z, n = state
    n_new = n + jnp.square(g)
    sigma = (jnp.sqrt(n_new) - jnp.sqrt(n)) / h["lr"]
    z_new = z + g - sigma * w
    wn = jnp.where(
        jnp.abs(z_new) <= h["lamda1"], jnp.zeros_like(w),
        -(z_new - jnp.sign(z_new) * h["lamda1"])
        / ((h["beta"] + jnp.sqrt(n_new)) / h["lr"] + h["wd"]))
    return wn, (z_new, n_new)


def _ftml_fused(w, g, state, h):
    import jax.numpy as jnp
    g = _rule_prep(g, h) + h["wd"] * w
    d, v, z = state
    v_new = h["beta2"] * v + h["one_minus_beta2"] * jnp.square(g)
    d_new = h["d_coef"] * (jnp.sqrt(v_new / h["v_coef"]) + h["epsilon"])
    sigma = d_new - h["beta1"] * d
    z_new = h["beta1"] * z + h["one_minus_beta1"] * g - sigma * w
    return -z_new / d_new, (d_new, v_new, z_new)


def _adamax_fused(w, g, state, h):
    import jax.numpy as jnp
    g = g * h["rescale_grad"] + h["wd"] * w
    if "clip_gradient" in h:
        g = jnp.clip(g, -h["clip_gradient"], h["clip_gradient"])
    m, u = state
    m_new = h["beta1"] * m + h["one_minus_beta1"] * g
    u_new = jnp.maximum(h["beta2"] * u, jnp.abs(g))
    return w - h["lr"] * m_new / u_new, (m_new, u_new)


def _test_fused(w, g, state, h):
    return (w - h["lr"] * g * h["rescale_grad"], (state[0] + g,))


def fused_state_arrays(state):
    """Normalize an optimizer state (None | NDArray | tuple) to the flat
    tuple of NDArray buffers a fused rule consumes/produces."""
    if state is None:
        return ()
    if isinstance(state, NDArray):
        return (state,)
    return tuple(state)


class Optimizer(object):
    """Base optimizer (reference: python/mxnet/optimizer.py:444)."""

    opt_registry = {}

    @staticmethod
    def register(klass):
        """Register a subclass under its lowercased name."""
        assert isinstance(klass, type)
        name = klass.__name__.lower()
        if name in Optimizer.opt_registry:
            logging.warning("New optimizer %s is overriding existing "
                            "optimizer %s", klass.__name__, name)
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        if name.lower() in Optimizer.opt_registry:
            return Optimizer.opt_registry[name.lower()](**kwargs)
        raise ValueError("Cannot find optimizer %s" % name)

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        if param_idx2name is None:
            param_idx2name = {}
        assert isinstance(param_idx2name, dict), \
            "param_idx2name should be a dict of param indexes to names."
        self.idx2name = param_idx2name.copy()
        self.sym_info = (sym.attr_dict(), sym.list_arguments()) if sym is not None else ()
        self.param_dict = param_dict if param_dict else {}
        self.set_lr_mult({})
        self.set_wd_mult({})

    def create_state(self, index, weight):
        """Create auxiliary state for the given weight. Override."""

    def create_state_multi_precision(self, index, weight):
        """Low-precision weights get an fp32 master copy when
        multi_precision is on; state layout is (state, weight32)."""
        if self.multi_precision and weight.dtype == numpy.float16:
            weight_master_copy = weight.astype(numpy.float32)
            return (self.create_state(index, weight_master_copy),
                    weight_master_copy)
        if weight.dtype == numpy.float16 and not self.multi_precision:
            logging.warning("Accumulating with float16 in optimizer can lead "
                            "to poor accuracy or slow convergence. Consider "
                            "using multi_precision=True option.")
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        """Update the weight given gradient and state. Override."""
        raise NotImplementedError()

    # -- fused train-step support ------------------------------------------
    def fused_rule(self):
        """Pure functional update rule for the fused train-step path
        (Executor.train_step / fused_apply):
        ``rule(weight, grad, state_tuple, hyper) -> (new_w, new_state_tuple)``
        on raw jax arrays. None (the default) = no pure rule; fused
        callers fall back to the per-param update() path."""
        return None

    def fused_hyper(self, index):
        """Per-step scalar hyperparameters for ``fused_rule`` — advances
        the same update-count/lr-schedule bookkeeping as update(), so a
        fused and an unfused run see identical schedules."""
        self._update_count(index)
        h = {"lr": float(self._get_lr(index)),
             "wd": float(self._get_wd(index)),
             "rescale_grad": float(self.rescale_grad)}
        if self.clip_gradient is not None and self.clip_gradient > 0:
            h["clip_gradient"] = float(self.clip_gradient)
        return h

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype == numpy.float16:
            weight_master_copy = state[1]
            grad32 = grad.astype(numpy.float32)
            self.update(index, weight_master_copy, grad32, state[0])
            weight._set_data(weight_master_copy.astype(weight.dtype)._data)
        else:
            self.update(index, weight, grad, state)

    @property
    def learning_rate(self):
        """Current learning rate incl. scheduler (reference:
        python/mxnet/optimizer.py learning_rate property)."""
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise UserWarning("LRScheduler of the optimizer has already been "
                              "defined. Note that set_learning_rate can mutate "
                              "the value of the learning rate of the optimizer "
                              "only when the LRScheduler of the optimizer is "
                              "undefined.")
        self.lr = lr

    def set_lr_mult(self, args_lr_mult):
        """Set individual learning-rate multipliers for parameters."""
        self.lr_mult = {}
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__lr_mult__" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        """Set individual weight-decay multipliers. By default biases and
        norm parameters (names not ending in _weight/_gamma) get wd 0."""
        self.wd_mult = {}
        for n in self.idx2name.values():
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__wd_mult__" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def __getstate__(self):
        ret = self.__dict__.copy()
        # jitted fused-update programs are not picklable (and rebuild
        # cheaply on first use after deserialization)
        ret.pop("_fused_apply_cache", None)
        return ret

    def __setstate__(self, state):
        self.__dict__ = state


register = Optimizer.register
create = Optimizer.create_optimizer


def _common_kwargs(opt):
    kw = {"rescale_grad": opt.rescale_grad}
    if opt.clip_gradient is not None:
        kw["clip_gradient"] = opt.clip_gradient
    return kw


def _is_row_sparse(grad):
    from .ndarray.sparse import RowSparseNDArray
    return isinstance(grad, RowSparseNDArray)


@register
class SGD(Optimizer):
    """SGD with momentum and optional multi-precision
    (reference: optimizer.py SGD; kernels src/operator/optimizer_op.cc)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def fused_rule(self):
        from . import config
        if config.get("MXNET_PALLAS_FUSED_UPDATE"):
            return _sgd_fused_pallas
        return _sgd_fused

    def fused_hyper(self, index):
        h = super().fused_hyper(index)
        h["momentum"] = float(self.momentum)
        return h

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        kw = _common_kwargs(self)
        if _is_row_sparse(grad):
            if not self.lazy_update:
                grad = grad.todense()
            else:
                # lazy path: touch only the rows present in the gradient
                # (reference: optimizer_op.cc SGDUpdateRspImpl)
                from .ops import sparse_ops as _sk
                clip = self.clip_gradient
                if state is not None:
                    w, m = _sk.rsp_sgd_mom_update(
                        weight._data, state._data, grad.indices, grad.data,
                        lr, self.momentum, wd, self.rescale_grad, clip)
                    weight._set_data(w)
                    state._set_data(m)
                else:
                    weight._set_data(_sk.rsp_sgd_update(
                        weight._data, grad.indices, grad.data, lr, wd,
                        self.rescale_grad, clip))
                return
        if state is not None:
            nd.sgd_mom_update(weight, grad, state, lr=lr, wd=wd,
                              momentum=self.momentum, **kw)
        else:
            nd.sgd_update(weight, grad, lr=lr, wd=wd, **kw)

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype == numpy.float16:
            mom, w32 = state
            self._update_count(index)
            lr, wd = self._get_lr(index), self._get_wd(index)
            kw = _common_kwargs(self)
            if mom is not None:
                nd.mp_sgd_mom_update(weight, grad, mom, w32, lr=lr, wd=wd,
                                     momentum=self.momentum, **kw)
            else:
                nd.mp_sgd_update(weight, grad, w32, lr=lr, wd=wd, **kw)
        else:
            self.update(index, weight, grad, state)


@register
class Signum(Optimizer):
    """Sign-of-gradient SGD with momentum (reference: optimizer.py Signum)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def fused_rule(self):
        return _signum_fused

    def fused_hyper(self, index):
        h = super().fused_hyper(index)
        h["momentum"] = float(self.momentum)
        h["one_minus_momentum"] = 1.0 - float(self.momentum)
        h["wdlh_coef"] = 1.0 - h["lr"] * float(self.wd_lh)
        h["lr_wd"] = h["lr"] * h["wd"]
        return h

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        kw = _common_kwargs(self)
        if state is not None:
            nd.signum_update(weight, grad, state, lr=lr, wd=wd,
                             momentum=self.momentum, wd_lh=self.wd_lh, **kw)
        else:
            nd.signsgd_update(weight, grad, lr=lr, wd=wd, **kw)


@register
class NAG(Optimizer):
    """Nesterov accelerated gradient (reference: optimizer.py NAG)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def fused_rule(self):
        return _nag_fused

    def fused_hyper(self, index):
        h = super().fused_hyper(index)
        h["momentum"] = float(self.momentum)
        return h

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        kw = _common_kwargs(self)
        if state is not None:
            nd.nag_mom_update(weight, grad, state, lr=lr, wd=wd,
                              momentum=self.momentum, **kw)
        else:
            nd.sgd_update(weight, grad, lr=lr, wd=wd, **kw)


@register
class Adam(Optimizer):
    """Adam (reference: optimizer.py Adam; kernel adam_update)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def fused_rule(self):
        from . import config
        if config.get("MXNET_PALLAS_FUSED_UPDATE"):
            return _adam_fused_pallas
        return _adam_fused

    def fused_hyper(self, index):
        h = super().fused_hyper(index)
        t = self._index_update_count[index]
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        h["lr"] = float(h["lr"] * (numpy.sqrt(coef2) / coef1))
        h["beta1"] = float(self.beta1)
        h["beta2"] = float(self.beta2)
        h["one_minus_beta1"] = 1.0 - float(self.beta1)
        h["one_minus_beta2"] = 1.0 - float(self.beta2)
        h["epsilon"] = float(self.epsilon)
        return h

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr *= numpy.sqrt(coef2) / coef1
        mean, var = state
        if _is_row_sparse(grad):
            if not self.lazy_update:
                grad = grad.todense()
            else:
                # lazy Adam (reference: optimizer_op.cc AdamUpdateRspImpl)
                from .ops import sparse_ops as _sk
                w, m, v = _sk.rsp_adam_update(
                    weight._data, mean._data, var._data, grad.indices,
                    grad.data, lr, self.beta1, self.beta2, self.epsilon,
                    wd, self.rescale_grad, self.clip_gradient)
                weight._set_data(w)
                mean._set_data(m)
                var._set_data(v)
                return
        kw = _common_kwargs(self)
        nd.adam_update(weight, grad, mean, var, lr=lr, wd=wd,
                       beta1=self.beta1, beta2=self.beta2,
                       epsilon=self.epsilon, **kw)


@register
class AdaGrad(Optimizer):
    """AdaGrad (reference: optimizer.py AdaGrad)."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def fused_rule(self):
        return _adagrad_fused

    def fused_hyper(self, index):
        h = super().fused_hyper(index)
        h["eps"] = float(self.float_stable_eps)
        if self.clip_gradient is not None:
            # the eager update() clips whenever clip_gradient is set
            # (not only when > 0, unlike the fused kernels)
            h["clip_gradient"] = float(self.clip_gradient)
        return h

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        history = state
        history += grad * grad
        div = grad / (history.sqrt() + self.float_stable_eps)
        weight._set_data((weight - lr * (div + weight * wd))._data)


@register
class RMSProp(Optimizer):
    """RMSProp, plain (Tieleman) or centered (Graves)
    (reference: optimizer.py RMSProp; kernels rmsprop/rmspropalex_update)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),  # n
                    zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),  # g
                    zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))  # delta
        return zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def fused_rule(self):
        return _rmsprop_fused

    def fused_hyper(self, index):
        h = super().fused_hyper(index)
        h["gamma1"] = float(self.gamma1)
        h["one_minus_gamma1"] = 1.0 - float(self.gamma1)
        h["epsilon"] = float(self.epsilon)
        if self.centered:
            h["gamma2"] = float(self.gamma2)
        if self.clip_weights is not None and self.clip_weights > 0:
            h["clip_weights"] = float(self.clip_weights)
        return h

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        kw = _common_kwargs(self)
        if self.clip_weights:
            kw["clip_weights"] = self.clip_weights
        if not self.centered:
            nd.rmsprop_update(weight, grad, state, lr=lr, wd=wd,
                              gamma1=self.gamma1, epsilon=self.epsilon, **kw)
        else:
            n, g, delta = state
            nd.rmspropalex_update(weight, grad, n, g, delta, lr=lr, wd=wd,
                                  gamma1=self.gamma1, gamma2=self.gamma2,
                                  epsilon=self.epsilon, **kw)


@register
class AdaDelta(Optimizer):
    """AdaDelta (reference: optimizer.py AdaDelta)."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def fused_rule(self):
        return _adadelta_fused

    def fused_hyper(self, index):
        h = super().fused_hyper(index)
        h["rho"] = float(self.rho)
        h["one_minus_rho"] = 1.0 - float(self.rho)
        h["epsilon"] = float(self.epsilon)
        if self.clip_gradient is not None:
            # eager update() clips whenever clip_gradient is set
            h["clip_gradient"] = float(self.clip_gradient)
        return h

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        acc_g, acc_delta = state
        acc_g._set_data((self.rho * acc_g + (1.0 - self.rho) * grad * grad)._data)
        current_delta = ((acc_delta + self.epsilon).sqrt()
                         / (acc_g + self.epsilon).sqrt()) * grad
        acc_delta._set_data(
            (self.rho * acc_delta
             + (1.0 - self.rho) * current_delta * current_delta)._data)
        weight._set_data((weight - current_delta - wd * weight)._data)


@register
class Ftrl(Optimizer):
    """FTRL-proximal (reference: optimizer.py Ftrl; kernel ftrl_update)."""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),  # z
                zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))  # n

    def fused_rule(self):
        return _ftrl_fused

    def fused_hyper(self, index):
        h = super().fused_hyper(index)
        h["lamda1"] = float(self.lamda1)
        h["beta"] = float(self.beta)
        return h

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        z, n = state
        kw = _common_kwargs(self)
        nd.ftrl_update(weight, grad, z, n, lr=lr, wd=wd, lamda1=self.lamda1,
                       beta=self.beta, **kw)


@register
class FTML(Optimizer):
    """FTML (reference: optimizer.py FTML; kernel ftml_update)."""

    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),  # d
                zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),  # v
                zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))  # z

    def fused_rule(self):
        return _ftml_fused

    def fused_hyper(self, index):
        h = super().fused_hyper(index)
        t = self._index_update_count[index]
        # host-fold the scalar coefficients exactly as the ftml_update
        # kernel folds its python attrs, for bitwise fused/unfused parity
        h["beta1"] = float(self.beta1)
        h["one_minus_beta1"] = 1.0 - float(self.beta1)
        h["beta2"] = float(self.beta2)
        h["one_minus_beta2"] = 1.0 - float(self.beta2)
        h["epsilon"] = float(self.epsilon)
        h["d_coef"] = (1.0 - self.beta1 ** t) / h["lr"]
        h["v_coef"] = 1.0 - self.beta2 ** t
        return h

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        d, v, z = state
        kw = {"rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            kw["clip_grad"] = self.clip_gradient
        nd.ftml_update(weight, grad, d, v, z, lr=lr, wd=wd, beta1=self.beta1,
                       beta2=self.beta2, epsilon=self.epsilon, t=t, **kw)


@register
class Adamax(Optimizer):
    """AdaMax, Adam with infinity norm (reference: optimizer.py Adamax)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        return (zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def fused_rule(self):
        return _adamax_fused

    def fused_hyper(self, index):
        h = super().fused_hyper(index)
        t = self._index_update_count[index]
        h["lr"] = float(h["lr"] / (1.0 - self.beta1 ** t))
        h["beta1"] = float(self.beta1)
        h["one_minus_beta1"] = 1.0 - float(self.beta1)
        h["beta2"] = float(self.beta2)
        if self.clip_gradient is not None:
            # eager update() clips whenever clip_gradient is set
            h["clip_gradient"] = float(self.clip_gradient)
        return h

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        lr /= (1.0 - self.beta1 ** t)
        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        m_t, u_t = state
        m_t._set_data((self.beta1 * m_t + (1.0 - self.beta1) * grad)._data)
        u_t._set_data(nd.broadcast_maximum(self.beta2 * u_t, grad.abs())._data)
        weight._set_data((weight - lr * m_t / u_t)._data)


@register
class Nadam(Optimizer):
    """Nesterov Adam (reference: optimizer.py Nadam)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        return (zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        momentum_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
        momentum_t_1 = self.beta1 * (1.0 - 0.5 * 0.96 ** ((t + 1) * self.schedule_decay))
        self.m_schedule = self.m_schedule * momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1
        m_t, v_t = state
        m_t._set_data((self.beta1 * m_t + (1.0 - self.beta1) * grad)._data)
        v_t._set_data((self.beta2 * v_t + (1.0 - self.beta2) * grad * grad)._data)
        grad_prime = grad / (1.0 - self.m_schedule)
        m_t_prime = m_t / (1.0 - m_schedule_next)
        v_t_prime = v_t / (1.0 - self.beta2 ** t)
        m_t_bar = (1.0 - momentum_t) * grad_prime + momentum_t_1 * m_t_prime
        weight._set_data(
            (weight - lr * m_t_bar / (v_t_prime.sqrt() + self.epsilon))._data)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (reference: optimizer.py SGLD)."""

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        from .ndarray import random as _ndrandom
        noise = _ndrandom.normal(0, numpy.sqrt(lr), shape=weight.shape,
                                 dtype=weight.dtype, ctx=weight.context)
        weight._set_data(
            (weight - lr / 2 * (grad + wd * weight) + noise)._data)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference: optimizer.py DCASGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        mom, previous_weight = state
        delta = -lr * (grad + wd * weight + self.lamda
                       * grad * grad * (weight - previous_weight))
        if mom is not None:
            mom._set_data((mom * self.momentum + delta)._data)
            delta = mom
        previous_weight._set_data(weight._data)
        weight._set_data((weight + delta)._data)


@register
class LBSGD(Optimizer):
    """Large-Batch SGD (reference: optimizer.py:672 LBSGD).

    Per layer, gradients accumulate for ``batch_scale`` micro-batches;
    then ONE momentum-SGD step applies with the learning rate scaled by
    the warmup schedule ('linear' / 'power2' / 'sqrt' toward
    batch_scale over warmup_epochs) or by the LARS trust ratio
    sqrt(||w||^2 / (||g||^2 + wd*||w||^2)) when
    warmup_strategy='lars'. The standard recipe for scaling batch size
    with worker count — particularly relevant on pod-scale dp meshes.
    """

    def __init__(self, momentum=0.0, warmup_strategy="linear",
                 warmup_epochs=5, batch_scale=1, updates_per_epoch=32,
                 begin_epoch=0, num_epochs=60, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.warmup_strategy = warmup_strategy
        self.warmup_epochs = warmup_epochs
        self.batch_scale = int(batch_scale)
        self.updates_per_epoch = updates_per_epoch
        self.init_updates = begin_epoch * updates_per_epoch
        self.num_epochs = num_epochs
        self._cum = {}                     # index -> [cum_grad, n]

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def _warmup_mult(self, nup):
        import math
        nwup = self.warmup_epochs * self.updates_per_epoch
        maxmult = float(self.batch_scale)
        if nup >= nwup:
            return maxmult
        if nwup <= 1:
            return 1.0
        if self.warmup_strategy == "linear":
            return 1.0 + (maxmult - 1) * nup / nwup
        if self.warmup_strategy == "power2":
            return 1.0 + (maxmult - 1) * (nup * nup) / (nwup * nwup)
        if self.warmup_strategy == "sqrt":
            return 1.0 + (maxmult - 1) * math.sqrt(float(nup) / nwup)
        return 1.0

    def _lars(self, weight, grad, wd):
        import math
        w2 = float((weight * weight).asnumpy().sum())
        g2 = float((grad * grad).asnumpy().sum())
        lars = math.sqrt(w2 / (g2 + wd * w2 + 1e-18))
        return min(max(lars, 0.01), 100.0)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if _is_row_sparse(grad):
            grad = grad.todense()
        if self.batch_scale > 1:
            # accumulate per layer; the micro-batch counter is MONOTONIC
            # for the whole run (the reference's num_cums) so the warmup
            # schedule advances — only the accumulated gradient resets
            # at each macro-batch boundary
            cum = self._cum.get(index)
            if cum is None:
                self._cum[index] = cum = [grad.copy(), 1]
            elif cum[1] % self.batch_scale == 0:
                cum[0] = grad.copy()
                cum[1] += 1
            else:
                cum[0]._set_data((cum[0] + grad)._data)
                cum[1] += 1
            if cum[1] % self.batch_scale != 0:
                return                      # accumulating micro-batch
            grad = cum[0] / self.batch_scale
            nup = self.init_updates + cum[1]
        else:
            nup = self.init_updates + self.num_update
        if self.warmup_strategy == "lars":
            lr = lr * self._lars(weight, grad, wd)
        else:
            lr = lr * self._warmup_mult(nup)
        kw = _common_kwargs(self)
        if state is not None:
            nd.sgd_mom_update(weight, grad, state, lr=lr, wd=wd,
                              momentum=self.momentum, **kw)
        else:
            nd.sgd_update(weight, grad, lr=lr, wd=wd, **kw)


@register
class Test(Optimizer):
    """Test optimizer: simple accumulating SGD (reference: optimizer.py Test)."""

    def create_state(self, index, weight):
        return zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def fused_rule(self):
        return _test_fused

    def fused_hyper(self, index):
        # mirror update() exactly: raw self.lr, no scheduler/multipliers,
        # no update-count bookkeeping
        return {"lr": float(self.lr),
                "rescale_grad": float(self.rescale_grad)}

    def update(self, index, weight, grad, state):
        weight._set_data((weight - self.lr * grad * self.rescale_grad)._data)
        state._set_data((state + grad)._data)


class Updater(object):
    """Applies an optimizer to (index, grad, weight) triples — the callable
    installed on KVStore (reference: optimizer.py Updater / get_updater)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def ensure_state(self, index, weight):
        """Lazily create (or context-sync a deserialized) state for
        ``index``; returns it. Shared by the per-param path below and the
        fused train step, so their bookkeeping can never drift."""
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(
                index, weight)
            self.states_synced[index] = True
        elif not self.states_synced[index]:
            self.states[index] = self.sync_state_context(self.states[index],
                                                         weight.context)
            self.states_synced[index] = True
        return self.states[index]

    def __call__(self, index, grad, weight):
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.ensure_state(index,
                                                                weight))

    def sync_state_context(self, state, context):
        if isinstance(state, NDArray):
            return state.as_in_context(context)
        if isinstance(state, numpy.ndarray):
            # deserialized states arrive as numpy (get_states converts for
            # pickling); rehydrate on the weight's device
            from .ndarray.ndarray import array
            return array(state, ctx=context)
        if isinstance(state, (tuple, list)):
            return type(state)(self.sync_state_context(i, context)
                               for i in state)
        return state

    def set_states(self, states):
        """Deserialize updater state (reference: Updater.set_states)."""
        states = pickle.loads(states)
        if isinstance(states, tuple) and len(states) == 2:
            self.states, self.optimizer = states
        else:
            self.states = states
        self.states_synced = dict.fromkeys(self.states.keys(), False)

    def get_states(self, dump_optimizer=False):
        states = {}
        for i, s in self.states.items():
            states[i] = _to_numpy_state(s)
        return pickle.dumps((states, self.optimizer) if dump_optimizer
                            else states)


def _to_numpy_state(state):
    if isinstance(state, NDArray):
        return state.asnumpy()
    if isinstance(state, (tuple, list)):
        return type(state)(_to_numpy_state(i) for i in state)
    return state


def get_updater(optimizer):
    return Updater(optimizer)


# ---------------------------------------------------------------------------
# fused whole-pytree update (one XLA program for every parameter)
# ---------------------------------------------------------------------------

def fused_apply(optimizer, items):
    """Apply ``optimizer`` to every ``(index, weight, grad, state)`` in
    ``items`` through ONE jitted XLA program with the weight and state
    buffers donated (input→output aliasing: in-place HBM update, a single
    Python→XLA dispatch instead of one per parameter — the Gluon Trainer
    analog of Executor.train_step).

    Returns True when the fused path ran (weights/states updated in
    place); False when this optimizer/configuration has no pure rule —
    the caller must then run the per-param update() path. Scalar
    hyperparameters (lr schedule, rescale_grad) are traced, so their
    value changes never recompile.
    """
    from .config import get as _cfg
    if not items or not _cfg("MXNET_FUSED_STEP"):
        return False
    rule = optimizer.fused_rule()
    if rule is None or optimizer.multi_precision:
        return False
    from .ndarray.sparse import BaseSparseNDArray
    for _i, w, g, _s in items:
        if isinstance(w, BaseSparseNDArray) or isinstance(g, BaseSparseNDArray):
            return False

    state_tuples = [fused_state_arrays(s) for (_i, _w, _g, s) in items]
    hyper = [optimizer.fused_hyper(i) for (i, _w, _g, _s) in items]

    cache = optimizer.__dict__.setdefault("_fused_apply_cache", {})
    # donation honors the same knob as the per-param update kernels
    # (ops/registry.py _donation_allowed)
    donate = bool(_cfg("MXNET_UPDATE_BUFFER_DONATION"))
    cache_key = (rule, len(items), donate)
    jfn = cache.get(cache_key)
    if jfn is None:
        import jax
        from .base import install_donation_warning_filter
        install_donation_warning_filter()

        def apply_all(ws, gs, ss, hs):
            new = [rule(w, g, s, h) for w, g, s, h in zip(ws, gs, ss, hs)]
            return [n[0] for n in new], [n[1] for n in new]

        jfn = jax.jit(apply_all, donate_argnums=(0, 2) if donate else ())
        cache[cache_key] = jfn

    ws = [w._data for (_i, w, _g, _s) in items]
    gs = [g._data for (_i, _w, g, _s) in items]
    ss = [tuple(a._data for a in tup) for tup in state_tuples]

    from . import telemetry as _tm
    token = _tm.dispatch_begin() if _tm._enabled else None
    new_ws, new_ss = jfn(ws, gs, ss, hyper)
    if token is not None:
        _tm.dispatch_end("fused_optimizer_update", token)

    for (item, nw, ns, tup) in zip(items, new_ws, new_ss, state_tuples):
        item[1]._set_data(nw)
        for tgt, val in zip(tup, ns):
            tgt._set_data(val)
    return True
