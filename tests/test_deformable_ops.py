"""Deformable conv, PSROIPooling, FFT/IFFT, count_sketch.

Reference behavior: src/operator/contrib/deformable_convolution.cc,
psroi_pooling.cc, fft.cc, ifft.cc, count_sketch.cc.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import ndarray as nd


def test_deformable_conv_zero_offsets_match_standard_conv():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    w = rng.randn(4, 3, 3, 3).astype(np.float32) * 0.2
    b = rng.randn(4).astype(np.float32)
    off = np.zeros((2, 2 * 9, 8, 8), np.float32)
    out_d = nd.contrib.DeformableConvolution(
        mx.nd.array(x), mx.nd.array(off), mx.nd.array(w), mx.nd.array(b),
        kernel=(3, 3), num_filter=4, pad=(1, 1))
    out_c = nd.Convolution(mx.nd.array(x), mx.nd.array(w), mx.nd.array(b),
                           kernel=(3, 3), num_filter=4, pad=(1, 1))
    np.testing.assert_allclose(out_d.asnumpy(), out_c.asnumpy(),
                               rtol=1e-4, atol=1e-4)


def test_deformable_conv_integer_offset_shifts_sampling():
    # kernel 1x1: an integer offset of (0, +1) samples the pixel to the
    # right, i.e. the output equals the input shifted left
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    w = np.ones((1, 1, 1, 1), np.float32)
    off = np.zeros((1, 2, 4, 4), np.float32)
    off[0, 1] = 1.0                       # x-offset +1
    out = nd.contrib.DeformableConvolution(
        mx.nd.array(x), mx.nd.array(off), mx.nd.array(w),
        kernel=(1, 1), num_filter=1, no_bias=True).asnumpy()
    expect = np.zeros_like(x)
    expect[..., :, :3] = x[..., :, 1:]    # shifted; border samples 0
    np.testing.assert_allclose(out, expect, atol=1e-5)


def test_deformable_conv_differentiable_wrt_offsets():
    from mxnet_tpu import autograd
    rng = np.random.RandomState(1)
    x = mx.nd.array(rng.randn(1, 2, 6, 6).astype(np.float32))
    w = mx.nd.array(rng.randn(2, 2, 3, 3).astype(np.float32) * 0.2)
    off = mx.nd.array(rng.uniform(-0.4, 0.4, (1, 18, 6, 6)).astype(
        np.float32))
    off.attach_grad()
    with autograd.record():
        y = nd.contrib.DeformableConvolution(
            x, off, w, kernel=(3, 3), num_filter=2, pad=(1, 1),
            no_bias=True)
        loss = nd.sum(y * y)
    loss.backward()
    g = off.grad.asnumpy()
    assert np.isfinite(g).all() and np.abs(g).max() > 0


def test_psroi_pooling_reads_dedicated_channel():
    ps, od = 2, 3
    N, H, W = 1, 6, 6
    data = np.zeros((N, od * ps * ps, H, W), np.float32)
    # give each (c, bin) plane a distinct constant
    for c in range(od):
        for g in range(ps * ps):
            data[0, c * ps * ps + g] = 10 * c + g
    rois = np.array([[0, 0, 0, 5, 5]], np.float32)
    out = nd.contrib.PSROIPooling(
        mx.nd.array(data), mx.nd.array(rois), spatial_scale=1.0,
        output_dim=od, pooled_size=ps).asnumpy()
    assert out.shape == (1, od, ps, ps)
    for c in range(od):
        for py in range(ps):
            for px in range(ps):
                assert out[0, c, py, px] == 10 * c + (py * ps + px)


def test_fft_ifft_roundtrip_and_numpy_match():
    rng = np.random.RandomState(0)
    x = rng.randn(3, 8).astype(np.float32)
    spec = nd.contrib.fft(mx.nd.array(x)).asnumpy()
    ref = np.fft.fft(x, axis=-1)
    np.testing.assert_allclose(spec[:, 0::2], ref.real, rtol=1e-4,
                               atol=1e-4)
    np.testing.assert_allclose(spec[:, 1::2], ref.imag, rtol=1e-4,
                               atol=1e-4)
    back = nd.contrib.ifft(mx.nd.array(spec)).asnumpy() / 8.0
    np.testing.assert_allclose(back, x, rtol=1e-4, atol=1e-4)


def test_count_sketch_scatter_add_with_signs():
    data = np.array([[1.0, 2.0, 3.0, 4.0]], np.float32)
    h = np.array([[0, 1, 0, 1]], np.float32)
    s = np.array([[1, -1, 1, 1]], np.float32)
    out = nd.contrib.count_sketch(
        mx.nd.array(data), mx.nd.array(h), mx.nd.array(s),
        out_dim=2).asnumpy()
    np.testing.assert_allclose(out, [[1 + 3, -2 + 4]])


def test_psroi_pooling_group_size_differs_from_pooled_size():
    # ps=4 bins but gs=2 score-map groups: bins map to groups by
    # floor(p * gs / ps) (reference psroi_pooling.cc)
    ps, gs, od = 4, 2, 1
    data = np.zeros((1, od * gs * gs, 8, 8), np.float32)
    for g in range(gs * gs):
        data[0, g] = g
    rois = np.array([[0, 0, 0, 7, 7]], np.float32)
    out = nd.contrib.PSROIPooling(
        mx.nd.array(data), mx.nd.array(rois), spatial_scale=1.0,
        output_dim=od, pooled_size=ps, group_size=gs).asnumpy()
    for py in range(ps):
        for px in range(ps):
            expect = (py * gs // ps) * gs + (px * gs // ps)
            assert out[0, 0, py, px] == expect, (py, px)
