"""Logging helpers (reference: python/mxnet/log.py — a thin veneer over
the stdlib with a compact colored formatter)."""
from __future__ import annotations

import logging
import sys

__all__ = ["get_logger", "getLogger", "DEBUG", "INFO", "WARNING",
           "ERROR", "NOTSET"]

DEBUG = logging.DEBUG
INFO = logging.INFO
WARNING = logging.WARNING
ERROR = logging.ERROR
NOTSET = logging.NOTSET

_FMT = "%(asctime)s %(levelname)s %(name)s: %(message)s"
_DATEFMT = "%m%d %H:%M:%S"


def get_logger(name=None, filename=None, filemode=None, level=WARNING):
    """A configured logger (reference: log.py:90). File handler when
    ``filename`` is given, stderr stream handler otherwise; repeated
    calls reuse the configured logger."""
    logger = logging.getLogger(name)
    if getattr(logger, "_mxnet_tpu_configured", False):
        return logger
    if filename:
        handler = logging.FileHandler(filename, filemode or "a")
    else:
        handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(_FMT, _DATEFMT))
    logger.addHandler(handler)
    logger.setLevel(level)
    logger._mxnet_tpu_configured = True
    return logger


def getLogger(name=None, filename=None, filemode=None, level=WARNING):
    """Deprecated alias (reference: log.py:80)."""
    import warnings
    warnings.warn("getLogger is deprecated, use get_logger",
                  DeprecationWarning, stacklevel=2)
    return get_logger(name, filename, filemode, level)
