"""RTC: runtime kernel compilation (Pallas analog of NVRTC).

Reference: src/common/rtc.cc:35-61 + python/mxnet/rtc.py (CudaModule:
compile CUDA C at runtime, get_kernel(name, signature), launch on
NDArrays with grid/block dims).

TPU-native: the runtime-compiled kernel language is **Pallas**. A
``PallasModule`` takes Python source defining one or more Pallas kernel
functions (``def kernel(in_ref, ..., out_ref): ...``); ``get_kernel``
wraps one of them into a launchable bound to output shapes/specs, and
``Kernel.launch`` runs it on NDArrays through ``pl.pallas_call`` (jit
compiled on first launch, cached after — the Mosaic pipeline replaces
NVRTC). Off-TPU the kernel runs in pallas interpreter mode so the same
source is testable anywhere.
"""
from __future__ import annotations

from .base import MXNetError

__all__ = ["PallasModule", "Kernel"]


class PallasModule(object):
    """Compile Pallas kernel source at runtime (reference: rtc.py
    CudaModule; `exports` kept for API parity)."""

    def __init__(self, source, options=(), exports=()):
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu
        self._namespace = {"jax": jax, "jnp": jnp, "pl": pl,
                           "pltpu": pltpu}
        if isinstance(source, str):
            exec(compile(source, "<rtc>", "exec"), self._namespace)
        elif callable(source):
            self._namespace[source.__name__] = source
        else:
            raise MXNetError("source must be Python source text or a "
                             "kernel function")
        self.exports = tuple(exports)

    def get_kernel(self, name, signature=None):
        """Look up a kernel function and wrap it (the ``signature``
        string of the reference's cuda path is accepted and ignored —
        shapes/dtypes come from the launch arguments)."""
        fn = self._namespace.get(name)
        if fn is None or not callable(fn):
            raise MXNetError("kernel %r not found in module" % name)
        return Kernel(fn, name)


class Kernel(object):
    """A launchable Pallas kernel (reference: rtc.py Kernel.launch)."""

    def __init__(self, fn, name):
        self._fn = fn
        self.name = name
        self._cache = {}

    def launch(self, args, ctx=None, grid=None, out_shapes=None,
               interpret=None):
        """Run the kernel. ``args``: NDArrays (all inputs; outputs are
        returned). ``out_shapes``: list of (shape, dtype) for outputs,
        default = first input's. ``grid``: optional pallas grid."""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from .ndarray.ndarray import NDArray

        arrays = [a._data if isinstance(a, NDArray) else jnp.asarray(a)
                  for a in args]
        if out_shapes is None:
            out_shapes = [(arrays[0].shape, arrays[0].dtype)]
        if interpret is None:
            interpret = not all(
                d.platform == "tpu"
                for a in arrays for d in a.devices())
        key = (tuple((tuple(a.shape), str(a.dtype)) for a in arrays),
               tuple((tuple(s), str(d)) for s, d in out_shapes),
               grid, interpret)
        call = self._cache.get(key)
        if call is None:
            out_sds = [jax.ShapeDtypeStruct(tuple(s), d)
                       for s, d in out_shapes]
            kwargs = {"out_shape": out_sds[0] if len(out_sds) == 1
                      else out_sds, "interpret": interpret}
            if grid is not None:
                kwargs["grid"] = grid
            call = jax.jit(lambda *xs: pl.pallas_call(self._fn, **kwargs)(*xs))
            self._cache[key] = call
        out = call(*arrays)
        if isinstance(out, (tuple, list)):
            return [NDArray(o) for o in out]
        return NDArray(out)
