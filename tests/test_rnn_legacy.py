"""Legacy mx.rnn API: symbolic cells + BucketSentenceIter +
BucketingModule — the reference's classic bucketed LM workflow
(reference: python/mxnet/rnn/, tests/python/train/test_bucketing.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io as mio


def _sigmoid(v):
    return 1.0 / (1.0 + np.exp(-v))


def test_lstm_cell_unroll_matches_numpy():
    cell = mx.rnn.LSTMCell(num_hidden=8, prefix="l0_")
    outputs, states = cell.unroll(5, mx.sym.var("data"),
                                  merge_outputs=True, batch_size=2)
    exe = outputs.simple_bind(data=(2, 5, 4))
    rng = np.random.RandomState(0)
    vals = {}
    for n, a in exe.arg_dict.items():
        if n != "data":
            v = rng.randn(*a.shape).astype(np.float32) * 0.4
            a[:] = mx.nd.array(v)
            vals[n] = v
    x = rng.randn(2, 5, 4).astype(np.float32)
    exe.arg_dict["data"][:] = mx.nd.array(x)
    out = exe.forward(is_train=False)[0].asnumpy()

    h = np.zeros((2, 8), np.float32)
    c = np.zeros((2, 8), np.float32)
    ref = []
    for t in range(5):
        g = (x[:, t] @ vals["l0_i2h_weight"].T + vals["l0_i2h_bias"] +
             h @ vals["l0_h2h_weight"].T + vals["l0_h2h_bias"])
        i, f, ct, o = np.split(g, 4, axis=1)
        c = _sigmoid(f + 1.0) * c + _sigmoid(i) * np.tanh(ct)
        h = _sigmoid(o) * np.tanh(c)
        ref.append(h)
    np.testing.assert_allclose(out, np.stack(ref, 1), rtol=1e-5,
                               atol=1e-6)


def test_gru_cell_unroll_matches_numpy():
    cell = mx.rnn.GRUCell(num_hidden=6, prefix="g0_")
    outputs, _ = cell.unroll(3, mx.sym.var("data"), merge_outputs=True,
                             batch_size=2)
    exe = outputs.simple_bind(data=(2, 3, 5))
    rng = np.random.RandomState(1)
    vals = {}
    for n, a in exe.arg_dict.items():
        if n != "data":
            v = rng.randn(*a.shape).astype(np.float32) * 0.4
            a[:] = mx.nd.array(v)
            vals[n] = v
    x = rng.randn(2, 3, 5).astype(np.float32)
    exe.arg_dict["data"][:] = mx.nd.array(x)
    out = exe.forward(is_train=False)[0].asnumpy()

    h = np.zeros((2, 6), np.float32)
    ref = []
    for t in range(3):
        gi = x[:, t] @ vals["g0_i2h_weight"].T + vals["g0_i2h_bias"]
        gh = h @ vals["g0_h2h_weight"].T + vals["g0_h2h_bias"]
        ir, iz, inn = np.split(gi, 3, axis=1)
        hr, hz, hn = np.split(gh, 3, axis=1)
        r = _sigmoid(ir + hr)
        z = _sigmoid(iz + hz)
        n = np.tanh(inn + r * hn)
        h = z * h + (1 - z) * n
        ref.append(h)
    np.testing.assert_allclose(out, np.stack(ref, 1), rtol=1e-5,
                               atol=1e-6)


def test_stacked_bidirectional_fused_shapes():
    # FusedRNNCell = stacked (+bidirectional) unfused cells on TPU
    cell = mx.rnn.FusedRNNCell(num_hidden=4, num_layers=2, mode="lstm",
                               bidirectional=True, prefix="f_")
    outputs, states = cell.unroll(6, mx.sym.var("data"),
                                  merge_outputs=True, batch_size=3)
    exe = outputs.simple_bind(data=(3, 6, 5))
    rng = np.random.RandomState(2)
    for n, a in exe.arg_dict.items():
        if n != "data":
            a[:] = mx.nd.array(rng.randn(*a.shape).astype(np.float32) * .3)
    exe.arg_dict["data"][:] = mx.nd.array(
        rng.randn(3, 6, 5).astype(np.float32))
    out = exe.forward(is_train=False)[0]
    assert out.shape == (3, 6, 8)          # 2 directions x num_hidden
    assert len(states) == 8                # 2 layers x 2 dirs x (h, c)
    assert np.isfinite(out.asnumpy()).all()


def test_bucket_sentence_iter():
    rng = np.random.RandomState(3)
    sentences = [list(rng.randint(1, 20, rng.randint(2, 17)))
                 for _ in range(80)]
    it = mx.rnn.BucketSentenceIter(sentences, batch_size=4,
                                   buckets=[8, 16], invalid_label=0)
    assert it.default_bucket_key == 16
    n = 0
    for batch in it:
        L = batch.bucket_key
        assert L in (8, 16)
        assert batch.data[0].shape == (4, L)
        assert batch.provide_data[0].shape == (4, L)
        # label is data shifted left one step
        d = batch.data[0].asnumpy()
        l = batch.label[0].asnumpy()
        np.testing.assert_array_equal(l[:, :-1], d[:, 1:])
        n += 1
    assert n > 0


def test_bucketing_module_lstm_lm_trains():
    """The classic workflow end-to-end: BucketSentenceIter feeding a
    shared-weight LSTM LM through BucketingModule.fit-style steps."""
    vocab, nh = 20, 16
    rng = np.random.RandomState(4)
    # learnable structure: next token = (token + 1) % vocab
    sentences = []
    for _ in range(60):
        start = rng.randint(0, vocab)
        ln = rng.randint(3, 9)
        sentences.append([(start + k) % vocab for k in range(ln)])
    it = mx.rnn.BucketSentenceIter(sentences, batch_size=4,
                                   buckets=[4, 8], invalid_label=-1)

    cell = mx.rnn.LSTMCell(num_hidden=nh, prefix="lm_")

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data, input_dim=vocab, output_dim=nh,
                                 name="embed")
        cell.reset()
        outputs, _ = cell.unroll(seq_len, embed, merge_outputs=True,
                                 batch_size=4)
        pred = mx.sym.Reshape(outputs, shape=(-1, nh))
        pred = mx.sym.FullyConnected(pred, num_hidden=vocab, name="fc")
        label_flat = mx.sym.Reshape(label, shape=(-1,))
        net = mx.sym.SoftmaxOutput(pred, label_flat, name="softmax",
                                   use_ignore=True, ignore_label=-1)
        return net, ("data",), ("softmax_label",)

    mod = mx.module.BucketingModule(sym_gen,
                                    default_bucket_key=it.default_bucket_key,
                                    context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})
    metric = mx.metric.Perplexity(ignore_label=-1)
    first = None
    for epoch in range(8):
        it.reset()
        metric.reset()
        for batch in it:
            mod.forward(batch, is_train=True)
            mod.update_metric(metric, batch.label)
            mod.backward()
            mod.update()
        ppl = metric.get()[1]
        if first is None:
            first = ppl
    assert ppl < first * 0.7, (first, ppl)


def test_lbsgd_and_fused_rnn_init():
    """Parity fillers: LBSGD (reference optimizer.py:672) and
    init.FusedRNN (per-gate delegation + LSTM forget bias)."""
    opt = mx.optimizer.create("lbsgd", learning_rate=0.1, batch_scale=2,
                              warmup_strategy="linear", warmup_epochs=0,
                              updates_per_epoch=1)
    w = mx.nd.array(np.ones((3,), np.float32))
    g = mx.nd.array(np.full((3,), 0.5, np.float32))
    st = opt.create_state(0, w)
    opt.update(0, w, g, st)
    np.testing.assert_allclose(w.asnumpy(), 1.0)       # accumulating
    opt.update(0, w, g, st)
    np.testing.assert_allclose(w.asnumpy(), 1.0 - 0.2 * 0.5, rtol=1e-6)

    init = mx.init.FusedRNN(mx.init.Xavier(), num_hidden=4, num_layers=1,
                            mode="lstm", forget_bias=2.0)
    b = mx.nd.zeros((16,))
    init(mx.init.InitDesc("lstm_l0_i2h_bias"), b)
    bb = b.asnumpy()
    assert (bb[4:8] == 2.0).all() and (bb[:4] == 0).all()
    wt = mx.nd.zeros((16, 8))
    init(mx.init.InitDesc("lstm_l0_i2h_weight"), wt)
    assert float(np.abs(wt.asnumpy()).sum()) > 0


def test_lstm_bucketing_example_cli(tmp_path):
    """The lstm_bucketing example CLI trains end-to-end (subprocess, as
    a user runs it)."""
    import os
    import subprocess
    import sys
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(root, "examples",
                                      "lstm_bucketing.py"),
         "--num-epochs", "2", "--num-hidden", "16", "--num-embed", "16",
         "--batch-size", "16", "--buckets", "8,16"],
        capture_output=True, text=True, timeout=420, env=env, cwd=root)
    assert r.returncode == 0, r.stderr[-1500:]
    assert "Train-perplexity" in r.stderr or "Train-perplexity" in r.stdout
