"""Symbolic graph API.

Reference: python/mxnet/symbol/symbol.py + src/executor/graph_executor.cc.

TPU-native design: a Symbol is a lightweight Python DAG over the same
declarative op registry the eager path uses. There are no nnvm passes —
binding a symbol traces the whole graph into ONE pure JAX function and
jits it, so shape/type inference is ``jax.eval_shape``, memory planning is
XLA buffer assignment, and op fusion/bulking (the reference's
InitOpSegs/PlanMemory, graph_executor.cc:637,673) is the XLA compiler.
"""
from __future__ import annotations

import json
import re
import threading

import numpy as _np

from ..base import MXNetError, np_dtype
from ..ops import registry as _reg

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json"]

# ops whose trailing inputs are auxiliary states (not gradient-trained;
# updated by the executor during training — reference: FInferStorageType
# aux handling & BatchNorm aux states, src/operator/nn/batch_norm.cc)
AUX_STATES = {
    "BatchNorm": ("moving_mean", "moving_var"),
    "BatchNorm_v1": ("moving_mean", "moving_var"),
    "SyncBatchNorm": ("moving_mean", "moving_var"),
}

# control-flow subgraph ops: inner aux updates ride as trailing outputs
_CF_OPS = ("_sym_foreach", "_sym_while_loop", "_sym_cond")


class _NameManager(threading.local):
    """Auto-naming for anonymous symbols (reference:
    python/mxnet/name.py NameManager)."""

    def __init__(self):
        self._counter = {}
        self.prefix = ""

    def get(self, hint):
        hint = hint.lower().lstrip("_")
        idx = self._counter.get(hint, 0)
        self._counter[hint] = idx + 1
        return "%s%s%d" % (self.prefix, hint, idx)


_name_mgr = _NameManager()


class AttrScope(object):
    """Scope applying attributes to every symbol created inside
    (reference: python/mxnet/attribute.py AttrScope; the model-parallel
    docs' `with mx.AttrScope(ctx_group='dev1'):` pattern). The stack is
    thread-local, like _NameManager."""

    _tls = threading.local()

    def __init__(self, **attrs):
        self._attrs = attrs

    @classmethod
    def _stack(cls):
        if not hasattr(cls._tls, "stack"):
            cls._tls.stack = []
        return cls._tls.stack

    def __enter__(self):
        AttrScope._stack().append(self._attrs)
        return self

    def __exit__(self, *exc):
        AttrScope._stack().pop()

    @staticmethod
    def _current_attrs():
        merged = {}
        for frame in AttrScope._stack():
            merged.update(frame)
        return merged


def _input_names(op):
    """Array-input parameter names of an op, derived from its pure-function
    signature (attrs are whatever appears in ``attr_defaults``)."""
    import inspect
    names = []
    for p in inspect.signature(op.fn).parameters.values():
        if p.kind not in (inspect.Parameter.POSITIONAL_OR_KEYWORD,
                          inspect.Parameter.POSITIONAL_ONLY):
            continue
        if p.name == "key" or p.name.startswith("_"):
            continue
        if p.name in op.attr_defaults:
            continue
        names.append((p.name, p.default is not inspect.Parameter.empty))
    return names


_node_serial = [0]


class _Node:
    """One graph node: an op application or a variable (op is None)."""

    __slots__ = ("op", "name", "attrs", "inputs", "is_aux", "in_names",
                 "serial")

    def __init__(self, op, name, attrs=None, inputs=(), is_aux=False,
                 in_names=None):
        self.op = op                    # op name string or None for variables
        self.name = name
        self.attrs = dict(attrs or {})
        self.inputs = list(inputs)      # list of (_Node, out_index)
        self.is_aux = is_aux
        # names of the op input slots actually wired, aligned with
        # ``inputs`` (optional inputs like bias may be skipped)
        self.in_names = in_names
        # creation order: control-flow tracing uses it to tell nodes
        # built INSIDE a body apart from closed-over outer nodes
        _node_serial[0] += 1
        self.serial = _node_serial[0]

    @property
    def is_var(self):
        return self.op is None


def _topo(entries):
    """Topological order of nodes reachable from output entries."""
    seen = {}
    order = []

    def visit(node):
        if id(node) in seen:
            return
        seen[id(node)] = node
        for (n, _i) in node.inputs:
            visit(n)
        order.append(node)

    for (n, _i) in entries:
        visit(n)
    return order


class Symbol(object):
    """Symbolic multi-output expression (reference: symbol.py Symbol)."""

    __slots__ = ("_entries",)

    def __init__(self, entries):
        self._entries = list(entries)   # list of (_Node, out_index)

    # -- basic introspection ----------------------------------------------
    @property
    def name(self):
        if len(self._entries) == 1:
            return self._entries[0][0].name
        return None

    def __repr__(self):
        if len(self._entries) == 1:
            return "<Symbol %s>" % self._entries[0][0].name
        return "<Symbol Grouped %s>" % ",".join(
            n.name for n, _ in self._entries)

    def __iter__(self):
        return (self[i] for i in range(len(self)))

    def __len__(self):
        return len(self.list_outputs())

    def __copy__(self):
        return Symbol(self._entries)

    def __deepcopy__(self, memo):
        return load_json(self.tojson())

    def copy(self):
        return load_json(self.tojson())

    def __getitem__(self, index):
        outputs = self.list_outputs()
        if isinstance(index, str):
            matches = [i for i, n in enumerate(outputs)
                       if n == index or n == index + "_output"]
            if not matches:
                raise ValueError("cannot find output %r" % index)
            index = matches[0]
        if not 0 <= index < len(outputs):
            raise IndexError("index %d out of range" % index)
        return Symbol([self._entries[index]])

    def debug_str(self):
        """Human-readable graph dump (reference: symbol.py debug_str)."""
        lines = []
        for node in _topo(self._entries):
            if node.is_var:
                lines.append("Variable:%s" % node.name)
                continue
            ins = ", ".join("%s[%d]" % (src.name, oi)
                            for src, oi in node.inputs)
            attrs = " ".join("%s=%r" % kv for kv in
                             sorted(node.attrs.items())
                             if not kv[0].startswith("__"))
            lines.append("Op:%s, Name=%s\n  inputs: %s%s"
                         % (node.op, node.name, ins,
                            ("\n  attrs: " + attrs) if attrs else ""))
        return "\n".join(lines) + "\n"

    def get_internals(self):
        """All intermediate outputs as a grouped symbol
        (reference: symbol.py get_internals)."""
        entries = []
        for node in _topo(self._entries):
            if node.is_var:
                entries.append((node, 0))
            else:
                for i in range(_n_outputs(node)):
                    entries.append((node, i))
        return Symbol(entries)

    def get_children(self):
        nodes = []
        seen = set()
        for n, _ in self._entries:
            for (c, ci) in n.inputs:
                if (id(c), ci) not in seen:
                    seen.add((id(c), ci))
                    nodes.append((c, ci))
        if not nodes:
            return None
        return Symbol(nodes)

    # -- argument / output listing ----------------------------------------
    def list_arguments(self):
        return [n.name for n in _topo(self._entries)
                if n.is_var and not n.is_aux]

    def list_outputs(self):
        outs = []
        for node, idx in self._entries:
            if node.is_var:
                outs.append(node.name)
            elif _n_outputs(node) == 1:
                outs.append(node.name + "_output")
            else:
                outs.append("%s_output%d" % (node.name, idx))
        return outs

    def list_auxiliary_states(self):
        return [n.name for n in _topo(self._entries)
                if n.is_var and n.is_aux]

    def list_inputs(self):
        return [n.name for n in _topo(self._entries) if n.is_var]

    # -- attributes --------------------------------------------------------
    def attr(self, key):
        if len(self._entries) == 1:
            attrs = self._entries[0][0].attrs
            if key in attrs:
                return attrs[key]
            # annotation attrs (ctx_group, lr_mult, ...) are stored
            # dunder-prefixed; the reference API looks them up bare
            return attrs.get("__%s__" % key)
        return None

    def list_attr(self):
        if len(self._entries) == 1:
            return {k: str(v) for k, v in self._entries[0][0].attrs.items()}
        return {}

    def attr_dict(self):
        out = {}
        for node in _topo(self._entries):
            if node.attrs:
                out[node.name] = {k: str(v) for k, v in node.attrs.items()}
        return out

    def _set_attr(self, **kwargs):
        for node, _ in self._entries:
            node.attrs.update(kwargs)

    # -- composition -------------------------------------------------------
    def __call__(self, *args, **kwargs):
        s = self.copy()
        s._compose(*args, **kwargs)
        return s

    def _compose(self, *args, **kwargs):
        """Replace free variables with other symbols
        (reference: symbol.py _compose)."""
        name = kwargs.pop("name", None)
        if name is not None and len(self._entries) == 1:
            self._entries[0][0].name = name
        if args:
            free = [n for n in _topo(self._entries) if n.is_var and not n.is_aux]
            if len(args) > len(free):
                raise MXNetError("too many positional arguments to compose")
            for node, sym in zip(free, args):
                _substitute(node, sym)
        for key, sym in kwargs.items():
            hit = [n for n in _topo(self._entries)
                   if n.is_var and n.name == key]
            if not hit:
                # single-op symbols compose by op input-slot name: the
                # auto-created variable is "<opname>_<slot>" (reference:
                # compose matches operator argument names)
                hit = [n for n in _topo(self._entries)
                       if n.is_var and n.name.endswith("_" + key)]
            if not hit:
                raise MXNetError("no variable named %r to compose" % key)
            _substitute(hit[0], sym)

    # -- shape / type inference -------------------------------------------
    def infer_shape(self, *args, **kwargs):
        try:
            res = self._infer_shape_impl(False, *args, **kwargs)
        except Exception as e:
            raise MXNetError("infer_shape error: %s" % e) from e
        return res

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        import jax
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        known = {}
        if args:
            for n, s in zip(arg_names, args):
                if s is not None:
                    known[n] = tuple(s)
        known.update({k: tuple(v) for k, v in kwargs.items() if v is not None})

        # propagate through the graph with eval_shape; unknown leaf shapes
        # are resolved by per-op deduction where possible (dense layers),
        # otherwise inference fails like the reference's InferShape.
        shapes, node_outs = _deduce_shapes(self, known, partial=partial,
                                           return_outs=True)
        if shapes is None:
            return None, None, None
        arg_shapes = [shapes.get(n) for n in arg_names]
        aux_shapes = [shapes.get(n) for n in aux_names]

        if partial and (None in arg_shapes or None in aux_shapes):
            # some inputs stay unknowable: report what IS known — incl.
            # any output whose own inputs were all deducible — and leave
            # the rest None (the reference's partial contract)
            outs = [shapes.get(node.name) if node.is_var
                    else node_outs.get((id(node), oi))
                    for node, oi in self._entries]
            return arg_shapes, outs, aux_shapes

        def build(name):
            return jax.ShapeDtypeStruct(shapes[name], _np.float32)

        fn = _graph_eval_fn(self, is_train=False)
        env = {n: build(n) for n in arg_names + aux_names}
        key = _rng_placeholder(self)
        outs = jax.eval_shape(lambda e, k: fn(e, k), env, key)
        out_shapes = [tuple(o.shape) for o in outs[0]]
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, *args, **kwargs):
        """Infer dtypes via jax.eval_shape with float32 defaults
        (reference: symbol.py infer_type)."""
        import jax
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        dtypes = dict(zip(arg_names, args))
        dtypes.update(kwargs)
        shapes = _deduce_shapes(self, {}, partial=True) or {}
        env = {}
        for n in arg_names + aux_names:
            env[n] = jax.ShapeDtypeStruct(
                shapes.get(n) or (1,), np_dtype(dtypes.get(n, _np.float32)))
        fn = _graph_eval_fn(self, is_train=False)
        key = _rng_placeholder(self)
        arg_types = [env[n].dtype for n in arg_names]
        aux_types = [env[n].dtype for n in aux_names]
        try:
            outs = jax.eval_shape(lambda e, k: fn(e, k), env, key)
            out_types = [_np.dtype(o.dtype) for o in outs[0]]
        except Exception:
            # shapes unknown (infer_type carries no shape info) — fall back
            # to the dominant input dtype, the reference's common case
            dom = arg_types[0] if arg_types else _np.dtype(_np.float32)
            out_types = [dom for _ in self._entries]
        return arg_types, out_types, aux_types

    # -- serialization -----------------------------------------------------
    def tojson(self):
        """Serialize to the reference's JSON graph format
        (nodes / arg_nodes / heads — python/mxnet/symbol/symbol.py save)."""
        nodes = _topo(self._entries)
        nid = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        for n in nodes:
            jnodes.append({
                "op": "null" if n.is_var else n.op,
                "name": n.name,
                "attrs": _json_attrs(n.attrs),
                "inputs": [[nid[id(src)], oi, 0] for (src, oi) in n.inputs],
            })
            if n.is_aux:
                jnodes[-1]["aux"] = True
            if n.in_names is not None:
                jnodes[-1]["in_names"] = list(n.in_names)
        heads = [[nid[id(n)], oi, 0] for (n, oi) in self._entries]
        arg_nodes = [i for i, n in enumerate(nodes) if n.is_var]
        return json.dumps({"nodes": jnodes, "arg_nodes": arg_nodes,
                           "heads": heads, "attrs": {"mxnet_version": ["int", 10300]}},
                          indent=2)

    def save(self, fname):
        # atomic: a crash mid-save must not tear an existing symbol file
        from ..checkpoint import atomic_writer
        with atomic_writer(fname, "w") as f:
            f.write(self.tojson())

    # -- binding -----------------------------------------------------------
    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    **kwargs):
        """Allocate arrays by inferred shape and bind
        (reference: symbol.py simple_bind → graph_executor.cc:1578)."""
        from ..executor import Executor
        from ..ndarray.ndarray import zeros
        from ..context import current_context
        ctx = ctx or current_context()
        arg_shapes, _, aux_shapes = self.infer_shape(**kwargs)
        if arg_shapes is None or any(s is None for s in arg_shapes):
            raise MXNetError("cannot infer shapes for all arguments; pass "
                             "input shapes to simple_bind")
        type_dict = type_dict or {}
        arg_names = self.list_arguments()
        args = [zeros(s, ctx=ctx, dtype=type_dict.get(n, _np.float32))
                for n, s in zip(arg_names, arg_shapes)]
        aux_names = self.list_auxiliary_states()
        aux = [zeros(s, ctx=ctx, dtype=type_dict.get(n, _np.float32))
               for n, s in zip(aux_names, aux_shapes)]
        return self.bind(ctx, args, grad_req=grad_req, aux_states=aux)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        if group2ctx:
            # manual model parallelism: ctx_group attrs -> devices
            # (reference: graph_executor.cc:1578-1620 group2ctx)
            from ..model_parallel import GroupExecutor
            return GroupExecutor(self, ctx, args, args_grad, grad_req,
                                 aux_states, group2ctx=group2ctx)
        from ..executor import Executor
        return Executor(self, ctx, args, args_grad, grad_req, aux_states)

    # -- eval --------------------------------------------------------------
    def eval(self, ctx=None, **kwargs):
        ex = self.bind(ctx, kwargs)
        return ex.forward()

    # -- gradient ----------------------------------------------------------
    def grad(self, wrt):
        raise MXNetError("symbol.grad is deprecated in the reference; "
                         "bind with grad_req and use backward")

    # -- arithmetic --------------------------------------------------------
    def _binop(self, other, op_name, scalar_op, reverse=False):
        if isinstance(other, Symbol):
            a, b = (other, self) if reverse else (self, other)
            return _apply_op(_reg.get_op(op_name), (a, b), {}, None)
        if isinstance(other, (int, float)):
            return _apply_op(_reg.get_op(scalar_op), (self,),
                             {"scalar": float(other)}, None)
        raise TypeError(type(other))

    def __add__(self, other):
        return self._binop(other, "elemwise_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binop(other, "elemwise_sub", "_minus_scalar")

    def __rsub__(self, other):
        if isinstance(other, (int, float)):
            return _apply_op(_reg.get_op("_rminus_scalar"), (self,),
                             {"scalar": float(other)}, None)
        return self._binop(other, "elemwise_sub", "_minus_scalar", reverse=True)

    def __mul__(self, other):
        return self._binop(other, "elemwise_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binop(other, "elemwise_div", "_div_scalar")

    def __rtruediv__(self, other):
        if isinstance(other, (int, float)):
            return _apply_op(_reg.get_op("_rdiv_scalar"), (self,),
                             {"scalar": float(other)}, None)
        return self._binop(other, "elemwise_div", "_div_scalar", reverse=True)

    def __pow__(self, other):
        return self._binop(other, "broadcast_power", "_power_scalar")

    def __neg__(self):
        return _apply_op(_reg.get_op("negative"), (self,), {}, None)

    # comparisons build graph nodes (reference: symbol.py __gt__ etc.;
    # __eq__/__ne__ stay identity — symbols live in dicts/sets)
    def __gt__(self, other):
        return self._binop(other, "_greater", "_greater_scalar")

    def __ge__(self, other):
        return self._binop(other, "_greater_equal",
                           "_greater_equal_scalar")

    def __lt__(self, other):
        return self._binop(other, "_lesser", "_lesser_scalar")

    def __le__(self, other):
        return self._binop(other, "_lesser_equal", "_lesser_equal_scalar")

    def reshape(self, shape, **kw):
        return _apply_op(_reg.get_op("Reshape"), (self,),
                         {"shape": tuple(shape), **kw}, None)

    def __hash__(self):
        return object.__hash__(self)

    def __eq__(self, other):
        return self is other

    def __bool__(self):
        raise TypeError(
            "Symbol has no truth value: comparisons build graph nodes "
            "(use sym.contrib.cond for data-dependent branching)")


def _n_outputs(node):
    op = _reg.get_op(node.op)
    return op.n_outputs(node.attrs)


def _rng_placeholder(symbol):
    """A ShapeDtypeStruct PRNG key when the graph contains RNG ops."""
    import jax
    if any((not n.is_var) and _reg.get_op(n.op).needs_rng
           for n in _topo(symbol._entries)):
        return jax.ShapeDtypeStruct((2,), _np.uint32)
    return None


def _substitute(var_node, sym):
    """Turn ``var_node`` into an alias of ``sym``'s single entry by mutating
    it in place (compose support)."""
    if not isinstance(sym, Symbol) or len(sym._entries) != 1:
        raise MXNetError("can only compose with single-output symbols")
    src, oi = sym._entries[0]
    if src.is_var:
        var_node.name = src.name
        var_node.attrs = dict(src.attrs)
        var_node.is_aux = src.is_aux
    else:
        var_node.op = src.op
        var_node.name = src.name
        var_node.attrs = dict(src.attrs)
        var_node.inputs = list(src.inputs)
        var_node.is_aux = False
        var_node.in_names = src.in_names


def _json_attrs(attrs):
    out = {}
    for k, v in attrs.items():
        if isinstance(v, tuple):
            v = list(v)
        out[k] = v
    return out


def _from_json_attr(v):
    if isinstance(v, list):
        return tuple(v)
    return v


def Variable(name, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, stype=None, **kwargs):
    """Create a symbolic variable (reference: symbol.py var)."""
    if not isinstance(name, str):
        raise TypeError("Expect a string for variable name")
    attrs = {}
    for k, v in {**AttrScope._current_attrs(), **dict(attr or {})}.items():
        # same annotation convention as _apply_op: dunder-prefixed
        attrs["__%s__" % k] = v
    if shape is not None:
        attrs["__shape__"] = tuple(shape)
    if lr_mult is not None:
        attrs["__lr_mult__"] = lr_mult
    if wd_mult is not None:
        attrs["__wd_mult__"] = wd_mult
    if dtype is not None:
        attrs["__dtype__"] = np_dtype(dtype).name
    if init is not None:
        if not isinstance(init, str):
            init = init.dumps()
        attrs["__init__"] = init
    if stype is not None:
        attrs["__storage_type__"] = stype
    attrs.update(kwargs)
    return Symbol([(_Node(None, name, attrs), 0)])


var = Variable


def Group(symbols):
    """Group symbols into one multi-output symbol."""
    entries = []
    for s in symbols:
        if not isinstance(s, Symbol):
            raise TypeError("Expected Symbol in Group")
        entries.extend(s._entries)
    return Symbol(entries)


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


def load_json(json_str):
    data = json.loads(json_str)
    nodes = []
    for jn in data["nodes"]:
        attrs = {k: _from_json_attr(v)
                 for k, v in (jn.get("attrs") or {}).items()}
        if jn["op"] == "null":
            node = _Node(None, jn["name"], attrs, is_aux=jn.get("aux", False))
        else:
            node = _Node(jn["op"], jn["name"], attrs,
                         in_names=jn.get("in_names"))
            node.inputs = [(nodes[i], oi) for (i, oi, _v) in jn["inputs"]]
        nodes.append(node)
    entries = [(nodes[i], oi) for (i, oi, _v) in data["heads"]]
    return Symbol(entries)


# ---------------------------------------------------------------------------
# op application (the symbol-side analog of ndarray.invoke_op)
# ---------------------------------------------------------------------------

def _apply_op(op, args, attrs, name):
    """Create a graph node applying ``op``; auto-creates variables for
    missing array inputs like the reference's symbol compose
    (e.g. fc1_weight)."""
    in_names = _input_names(op)
    inputs = {}
    pos = 0
    kw_syms = dict(attrs)
    attrs = {}
    annotations = dict(AttrScope._current_attrs())
    for k, v in kw_syms.items():
        if isinstance(v, Symbol):
            inputs[k] = v
        elif k == "attr" and isinstance(v, dict):
            # annotation attrs (ctx_group, lr_mult, ...) — reference
            # symbol attr dicts; stored dunder-prefixed so graph eval
            # can strip them from op kwargs
            annotations.update(v)
        else:
            attrs[k] = v
    for k, v in annotations.items():
        attrs.setdefault("__%s__" % k, v)

    def _variadic():
        # computed lazily: only the overflow/unknown-kw branches need it,
        # and inspect.signature is the dominant _apply_op cost
        import inspect as _inspect
        return any(p.kind is _inspect.Parameter.VAR_POSITIONAL
                   for p in _inspect.signature(op.fn).parameters.values())

    for a in args:
        if not isinstance(a, Symbol):
            raise TypeError("positional args to symbol ops must be Symbols, "
                            "got %s" % type(a))
        while pos < len(in_names) and in_names[pos][0] in inputs:
            pos += 1
        if pos >= len(in_names):
            if _variadic():
                # *args ops (Custom, concat-style): synthesize input slots
                in_names = list(in_names) + [("arg%d" % pos, False)]
            else:
                raise MXNetError("too many inputs for op %s" % op.name)
        inputs[in_names[pos][0]] = a
        pos += 1

    # keyword Symbols unknown to the signature (variadic ops only, e.g.
    # sym.Custom(data=x, op_type=...)): append them as extra input slots
    # in keyword order rather than dropping them silently
    unknown_kw = [k for k in inputs if k not in (n for n, _ in in_names)]
    if unknown_kw:
        if not _variadic():
            raise MXNetError("unknown input(s) %s for op %s"
                             % (unknown_kw, op.name))
        in_names = list(in_names) + [(k, False) for k in unknown_kw]

    if name is None:
        name = _name_mgr.get(op.name)
    aux_names = AUX_STATES.get(op.name, ())

    node_inputs = []
    wired_names = []
    for in_name, has_default in in_names:
        if in_name in inputs:
            sym = inputs[in_name]
            if len(sym._entries) != 1:
                raise MXNetError("op inputs must be single-output symbols")
            ent = sym._entries[0]
            if in_name in aux_names and ent[0].is_var:
                # an explicit variable wired into an aux slot IS an aux
                # state (mutable, not gradient-trained) — reference
                # semantics; gluon's symbol trace passes running stats
                # this way
                ent[0].is_aux = True
            node_inputs.append(ent)
            wired_names.append(in_name)
            continue
        # missing input: auto-create a variable (reference behavior), or
        # skip genuinely-optional inputs (e.g. bias under no_bias)
        if in_name == "bias" and attrs.get("no_bias", False):
            continue
        if has_default and in_name not in aux_names and in_name != "bias":
            continue
        vnode = _Node(None, "%s_%s" % (name, in_name),
                      is_aux=in_name in aux_names)
        node_inputs.append((vnode, 0))
        wired_names.append(in_name)

    node = _Node(op.name, name, attrs, node_inputs, in_names=wired_names)
    n_out = op.n_outputs(attrs)
    return Symbol([(node, i) for i in range(n_out)])


# ---------------------------------------------------------------------------
# graph evaluation: symbol -> pure JAX function (the executor's core)
# ---------------------------------------------------------------------------

def _graph_eval_fn(symbol, is_train):
    """Build ``fn(env: dict name->array, rng_key) -> (outputs, new_aux)``.

    ``env`` carries argument AND auxiliary values. ``new_aux`` is the dict
    of updated auxiliary states (BatchNorm moving stats under training) —
    functional state-passing instead of the reference's in-place aux
    mutation (src/operator/nn/batch_norm.cc aux update)."""
    nodes = _topo(symbol._entries)
    aux_updates = {}  # node id -> (moving_mean_name, moving_var_name)

    def fn(env, rng_key):
        import jax
        values = {}     # (id(node), out_idx) -> array
        new_aux = {}
        key_ct = 0
        for node in nodes:
            if node.is_var:
                if node.name not in env:
                    raise MXNetError("unbound variable %r" % node.name)
                values[(id(node), 0)] = env[node.name]
                continue
            op = _reg.get_op(node.op)
            attrs = {k: v for k, v in node.attrs.items()
                     if not k.startswith("__")}
            if "train_mode" in op.attr_defaults and "train_mode" not in attrs:
                attrs["train_mode"] = is_train
            arrs = [values[(id(src), oi)] for (src, oi) in node.inputs]
            if op.needs_rng:
                if rng_key is None:
                    raise MXNetError("graph contains RNG ops; executor "
                                     "must supply a key")
                sub = jax.random.fold_in(rng_key, key_ct)
                key_ct += 1
                arrs = [sub] + arrs
            if (node.op in AUX_STATES and is_train
                    and not attrs.get("use_global_stats", False)):
                # force batch-stat outputs so the executor can update
                # the moving statistics functionally
                attrs["output_mean_var"] = True
                out, mean, vvar = op.fn(*arrs, **attrs)
                mom = attrs.get("momentum", 0.9)
                mm_node, mv_node = [node.inputs[i][0] for i in
                                    _aux_input_positions(op, node)]
                new_aux[mm_node.name] = mom * env[mm_node.name] + (1 - mom) * mean
                new_aux[mv_node.name] = mom * env[mv_node.name] + (1 - mom) * vvar
                outs = (out,)
                if node.attrs.get("output_mean_var", False):
                    outs = (out, mean, vvar)
            else:
                out = op.fn(*arrs, **attrs)
                outs = tuple(out) if isinstance(out, (tuple, list)) else (out,)
                # control-flow subgraphs surface their inner aux
                # updates (BN moving stats) as trailing outputs
                cf_aux = attrs.get("aux_names", ()) \
                    if node.op in _CF_OPS else ()
                if cf_aux and is_train:
                    for nm, val in zip(cf_aux, outs[-len(cf_aux):]):
                        new_aux[nm] = val
            for i, o in enumerate(outs):
                values[(id(node), i)] = o
        outputs = tuple(values[(id(n), oi)] for (n, oi) in symbol._entries)
        return outputs, new_aux

    return fn


def _aux_input_positions(op, node):
    aux_names = AUX_STATES[node.op]
    wired = node.in_names
    if wired is None:
        # graph loaded without slot names: valid only if nothing optional
        # was skipped before the aux slots
        wired = [n for n, _d in _input_names(op)][:len(node.inputs)]
        assert all(a in wired for a in aux_names), \
            "cannot locate aux inputs of %s; op has skipped optional " \
            "inputs and the graph carries no slot names" % node.op
    return [wired.index(a) for a in aux_names]


def _deduce_shapes(symbol, known, partial=False, return_outs=False):
    """Best-effort leaf shape deduction. Strategy: variables with
    ``__shape__`` attrs or entries in ``known`` are fixed; remaining
    parameter shapes are deduced per consuming op (dense/conv/norm
    patterns) from already-known input shapes — covering the shapes the
    reference's FInferShape tables compute for the common layers."""
    nodes = _topo(symbol._entries)
    shapes = dict(known)
    for n in nodes:
        if n.is_var and n.name not in shapes:
            s = n.attrs.get("__shape__")
            if s:
                shapes[n.name] = tuple(s)

    # iterate: propagate outputs with eval_shape when all inputs known;
    # deduce parameter leaves from op semantics when data input known.
    import jax
    out_shapes = {}   # (id(node), idx) -> shape

    def entry_shape(src, oi):
        if src.is_var:
            return shapes.get(src.name)
        return out_shapes.get((id(src), oi))

    progress = True
    while progress:
        progress = False
        for node in nodes:
            if node.is_var:
                continue
            if all((id(node), i) in out_shapes
                   for i in range(_n_outputs(node))):
                continue
            in_shapes = [entry_shape(s, oi) for (s, oi) in node.inputs]
            if any(s is None for s in in_shapes):
                ded = _deduce_params(node, in_shapes, shapes)
                if ded:
                    progress = True
                continue
            op = _reg.get_op(node.op)
            attrs = {k: v for k, v in node.attrs.items()
                     if not k.startswith("__")}
            if "train_mode" in op.attr_defaults:
                attrs["train_mode"] = False
            args = [jax.ShapeDtypeStruct(s, _np.float32) for s in in_shapes]
            if op.needs_rng:
                args = [jax.ShapeDtypeStruct((2,), _np.uint32)] + args
            try:
                outs = jax.eval_shape(lambda *a: op.fn(*a, **attrs), *args)
            except Exception:
                if partial:
                    continue
                raise
            outs = outs if isinstance(outs, (tuple, list)) else (outs,)
            for i, o in enumerate(outs):
                out_shapes[(id(node), i)] = tuple(o.shape)
            progress = True

    missing = [n.name for n in nodes if n.is_var and n.name not in shapes]
    if missing and not partial:
        raise MXNetError("cannot infer shapes for %s" % missing)
    if return_outs:
        return shapes, out_shapes
    return shapes

    # (reference behavior note: InferShape solves a full constraint system;
    # here deduction covers the standard layer library, matching what the
    # Module/model-zoo paths require.)


def _deduce_cf_params(node, in_shapes, shapes):
    """Recurse shape deduction into a control-flow node's serialized
    subgraph(s): inner auto-created parameters (e.g. an RNN cell's
    weights inside a foreach body) are free inputs of the node, so the
    shapes found inside become outer leaf shapes."""
    attrs = node.attrs
    wired = node.in_names or ()
    by_slot = dict(zip(wired, in_shapes))

    def recurse(graph_json, known):
        try:
            sub = load_json(graph_json)
        except Exception:
            return False
        inner = dict(known)
        inner.update({k: v for k, v in shapes.items() if v is not None})
        deduced = _deduce_shapes(sub, inner, partial=True)
        changed = False
        for k, v in deduced.items():
            # bound placeholders (_cf...) are loop-internal names
            if k not in shapes and v is not None and \
                    not k.startswith("_cf"):
                shapes[k] = tuple(v)
                changed = True
        return changed

    changed = False
    if node.op == "_sym_foreach":
        known = {}
        dshape = by_slot.get("data") or (in_shapes[0] if in_shapes
                                         else None)
        if dshape:
            known[attrs.get("data_name", "")] = tuple(dshape[1:])
        for nm, sh in zip(attrs.get("state_names", ()),
                          in_shapes[1:1 + len(attrs.get("state_names",
                                                        ()))]):
            if sh is not None:
                known[nm] = tuple(sh)
        changed |= recurse(attrs.get("graph_json"), known)
    elif node.op == "_sym_while_loop":
        known = {}
        for nm, sh in zip(attrs.get("state_names", ()), in_shapes):
            if sh is not None:
                known[nm] = tuple(sh)
        changed |= recurse(attrs.get("cond_json"), known)
        changed |= recurse(attrs.get("body_json"), known)
    elif node.op == "_sym_cond":
        known = {}
        for nm, sh in zip(attrs.get("input_names", ()), in_shapes):
            if sh is not None:
                known[nm] = tuple(sh)
        for key in ("pred_json", "then_json", "else_json"):
            changed |= recurse(attrs.get(key), known)
    return changed


def _deduce_params(node, in_shapes, shapes):
    """Deduce missing parameter-leaf shapes for the core NN ops from the
    data input's shape (the analog of each op's FInferShape filling in
    weight shapes, e.g. fully_connected.cc FullyConnectedShape)."""
    op_name = node.op
    attrs = node.attrs
    ins = node.inputs
    if op_name in _CF_OPS:
        return _deduce_cf_params(node, in_shapes, shapes)

    def set_leaf(pos, shape):
        src, _ = ins[pos]
        if src.is_var and src.name not in shapes and shape is not None:
            shapes[src.name] = tuple(int(x) for x in shape)
            return True
        return False

    data_shape = in_shapes[0] if in_shapes else None
    changed = False
    if data_shape is None:
        return False
    if op_name == "FullyConnected":
        num_hidden = attrs.get("num_hidden")
        flatten = attrs.get("flatten", True)
        in_dim = (int(_np.prod(data_shape[1:])) if flatten
                  else data_shape[-1])
        changed |= set_leaf(1, (num_hidden, in_dim))
        if len(ins) > 2:
            changed |= set_leaf(2, (num_hidden,))
    elif op_name in ("Convolution", "Deconvolution"):
        num_filter = attrs.get("num_filter")
        kernel = attrs.get("kernel", ())
        num_group = attrs.get("num_group", 1)
        if op_name == "Convolution":
            wshape = (num_filter, data_shape[1] // num_group) + tuple(kernel)
        else:
            wshape = (data_shape[1], num_filter // num_group) + tuple(kernel)
        changed |= set_leaf(1, wshape)
        if len(ins) > 2:
            changed |= set_leaf(2, (num_filter,))
    elif op_name in ("BatchNorm", "SyncBatchNorm", "InstanceNorm"):
        axis = attrs.get("axis", 1)
        c = data_shape[axis % len(data_shape)]
        for pos in range(1, len(ins)):
            changed |= set_leaf(pos, (c,))
    elif op_name == "LayerNorm":
        axis = attrs.get("axis", -1)
        c = data_shape[axis % len(data_shape)]
        for pos in range(1, len(ins)):
            changed |= set_leaf(pos, (c,))
    elif op_name == "Embedding":
        changed |= set_leaf(1, (attrs.get("input_dim"),
                                attrs.get("output_dim")))
    elif op_name in ("SoftmaxOutput", "LinearRegressionOutput",
                     "LogisticRegressionOutput", "MAERegressionOutput"):
        # label shape mirrors data (leading dims)
        if len(ins) > 1:
            src, _ = ins[1]
            if src.is_var and src.name not in shapes:
                if op_name == "SoftmaxOutput":
                    shapes[src.name] = tuple(data_shape[:1])
                else:
                    shapes[src.name] = tuple(data_shape)
                changed = True
    return changed
