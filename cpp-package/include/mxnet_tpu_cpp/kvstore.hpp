// C++ KVStore wrapper over the general C ABI (include/mxnet_tpu/c_api.h).
// Capability analog of the reference's cpp-package/include/mxnet-cpp/
// kvstore.h: init/push/pull on string keys, rank/size queries — the
// aggregation layer a multi-worker C++ training loop drives.
#ifndef MXNET_TPU_CPP_KVSTORE_HPP_
#define MXNET_TPU_CPP_KVSTORE_HPP_

#include <map>
#include <stdexcept>
#include <string>
#include <vector>

#include "mxnet_tpu_cpp/ndarray.hpp"

namespace mxnet_tpu_cpp {

class KVStore {
 public:
  explicit KVStore(const std::string& type = "local") {
    Check(MXKVStoreCreate(type.c_str(), &handle_));
  }

  KVStore(const KVStore&) = delete;
  KVStore& operator=(const KVStore&) = delete;

  ~KVStore() {
    if (handle_ != nullptr) MXKVStoreFree(handle_);
  }

  void Init(const std::vector<std::string>& keys,
            const std::vector<const NDArray*>& vals) {
    Call(&MXKVStoreInit, keys, vals);
  }

  void Push(const std::vector<std::string>& keys,
            const std::vector<const NDArray*>& vals, int priority = 0) {
    CallP(&MXKVStorePush, keys, vals, priority);
  }

  void Pull(const std::vector<std::string>& keys,
            const std::vector<const NDArray*>& outs, int priority = 0) {
    CallP(&MXKVStorePull, keys, outs, priority);
  }

  void PushPull(const std::vector<std::string>& keys,
                const std::vector<const NDArray*>& vals,
                const std::vector<const NDArray*>& outs,
                int priority = 0) {
    if (keys.size() != vals.size() || keys.size() != outs.size())
      throw std::invalid_argument("PushPull: keys/vals/outs sizes differ");
    std::vector<const char*> ks;
    std::vector<NDArrayHandle> vh, oh;
    Marshal(keys, vals, &ks, &vh);
    for (const auto* o : outs) oh.push_back(o->handle());
    Check(MXKVStorePushPull(handle_, static_cast<uint32_t>(ks.size()),
                            ks.data(), vh.data(), oh.data(), priority));
  }

  // updater receives BORROWED handles (read recv, write local via the
  // sync-copy ABI); the caller keeps updater/state alive while pushes
  // can happen — same contract as the reference's C++ kvstore
  void SetUpdater(MXKVStoreStrUpdater* updater, void* state = nullptr) {
    if (updater == nullptr) {
      // clear: a NULL function pointer uninstalls bridge-side
      Check(MXKVStoreSetUpdater(handle_, nullptr, nullptr));
      return;
    }
    Check(MXKVStoreSetUpdaterEx(handle_, nullptr, updater, state));
  }

  void SetOptimizer(const std::string& name,
                    const std::map<std::string, std::string>& params = {}) {
    std::vector<const char*> ks, vs;
    MapToKV(params, &ks, &vs);
    Check(MXKVStoreSetOptimizer(handle_, name.c_str(),
                                static_cast<int>(ks.size()), ks.data(),
                                vs.data()));
  }

  void SetGradientCompression(
      const std::map<std::string, std::string>& params) {
    std::vector<const char*> ks, vs;
    MapToKV(params, &ks, &vs);
    Check(MXKVStoreSetGradientCompression(
        handle_, static_cast<uint32_t>(ks.size()), ks.data(), vs.data()));
  }

  void Barrier() { Check(MXKVStoreBarrier(handle_)); }

  int NumDeadNode(int node_id = 0, int timeout_sec = 60) const {
    int n = 0;
    Check(MXKVStoreGetNumDeadNode(handle_, node_id, &n, timeout_sec));
    return n;
  }

  static bool IsWorkerNode() {
    int r = 0;
    Check(MXKVStoreIsWorkerNode(&r));
    return r != 0;
  }

  static bool IsServerNode() {
    int r = 0;
    Check(MXKVStoreIsServerNode(&r));
    return r != 0;
  }

  static bool IsSchedulerNode() {
    int r = 0;
    Check(MXKVStoreIsSchedulerNode(&r));
    return r != 0;
  }

  std::string Type() const {
    const char* t = nullptr;
    Check(MXKVStoreGetType(handle_, &t));
    return t;
  }

  int Rank() const {
    int r = 0;
    Check(MXKVStoreGetRank(handle_, &r));
    return r;
  }

  int GroupSize() const {
    int n = 0;
    Check(MXKVStoreGetGroupSize(handle_, &n));
    return n;
  }

  KVStoreHandle handle() const { return handle_; }

 private:
  static void Marshal(const std::vector<std::string>& keys,
                      const std::vector<const NDArray*>& vals,
                      std::vector<const char*>* ks,
                      std::vector<NDArrayHandle>* hs) {
    if (keys.size() != vals.size())
      throw std::invalid_argument("KVStore: keys/arrays sizes differ");
    for (const auto& k : keys) ks->push_back(k.c_str());
    for (const auto* v : vals) hs->push_back(v->handle());
  }

  static void MapToKV(const std::map<std::string, std::string>& params,
                      std::vector<const char*>* ks,
                      std::vector<const char*>* vs) {
    for (const auto& kv : params) {
      ks->push_back(kv.first.c_str());
      vs->push_back(kv.second.c_str());
    }
  }

  template <typename Fn>
  void Call(Fn fn, const std::vector<std::string>& keys,
            const std::vector<const NDArray*>& vals) {
    std::vector<const char*> ks;
    std::vector<NDArrayHandle> hs;
    Marshal(keys, vals, &ks, &hs);
    Check(fn(handle_, static_cast<uint32_t>(ks.size()), ks.data(),
             hs.data()));
  }

  template <typename Fn>
  void CallP(Fn fn, const std::vector<std::string>& keys,
             const std::vector<const NDArray*>& vals, int priority) {
    std::vector<const char*> ks;
    std::vector<NDArrayHandle> hs;
    Marshal(keys, vals, &ks, &hs);
    Check(fn(handle_, static_cast<uint32_t>(ks.size()), ks.data(),
             hs.data(), priority));
  }

  KVStoreHandle handle_ = nullptr;
};

}  // namespace mxnet_tpu_cpp

#endif  // MXNET_TPU_CPP_KVSTORE_HPP_
