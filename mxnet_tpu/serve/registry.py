"""Model hot-swap: atomic engine replacement with zero dropped requests,
plus quantized-variant rollout (shadow A/B canary -> int8 flip).

A serving deployment updates weights (a new checkpoint from the training
fleet) without a restart: :meth:`ModelRegistry.swap` builds a NEW
:class:`InferenceEngine` from the new params blob, warms every bucket
(compiles finish before the swap — traffic never eats one), atomically
replaces the active engine, and gracefully drains the old one. Requests
already queued on the old engine flush through the old weights; requests
arriving after the swap run the new ones; nothing is dropped. The
rollout is observable via ``serving/swaps_total`` and the standard
engine metrics.

**Quantized serving** rides the same machinery
(mxnet_tpu/quantize/, docs/quantization.md):

* ``swap(quantized=artifact)`` — hot-swap to a calibrated int8
  :class:`~mxnet_tpu.quantize.ptq.QuantizedParams` artifact (its graph
  differs from the fp32 one, so the artifact carries its own symbol);
  drain semantics are IDENTICAL to a weight swap, and ``swap(bytes)``
  later rolls back to fp32.
* :meth:`enable_shadow` — before flipping, canary the artifact under
  REAL traffic: a configurable fraction of live requests is mirrored
  to a warmed shadow engine, per-request output drift lands in the
  ``quantize/shadow_drift`` histogram (surfaced on ``/metrics``) and a
  ``serve.shadow`` span in the request's trace (``/traces``). Shadow
  compares run on a side thread — they never add latency to, or fail,
  the primary request.
"""
from __future__ import annotations

import random as _random
import threading
from collections import deque

from .. import telemetry as _tm
from .. import tracing as _tr
from ..base import MXNetError
from .engine import EngineClosedError, InferenceEngine, ServeConfig

__all__ = ["ModelRegistry"]

# drift is an output-magnitude delta, not a latency: give the histogram
# magnitude-scaled buckets (a softmax-head drift of 1e-3 and a logit
# drift of 0.5 must land in different cells)
_DRIFT_BUCKETS = (1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 0.1, 1.0)

# compare-backlog bound: one compare thread drains pairs with blocking
# result() waits, so a shadow engine slower than the primary would
# otherwise grow the queue (and pin every entry's arrays) without limit
# on a long canary — past the bound new mirrors are dropped and counted
_SHADOW_PENDING_MAX = 256


def _resolve_quantized(quantized):
    """(symbol_json, param_bytes) from a QuantizedParams-like artifact,
    an on-disk artifact prefix, or an explicit pair."""
    if isinstance(quantized, str):
        from ..quantize.ptq import QuantizedParams
        quantized = QuantizedParams.load(quantized)
    if hasattr(quantized, "symbol_json") and hasattr(quantized,
                                                    "param_bytes"):
        return quantized.symbol_json, quantized.param_bytes()
    if isinstance(quantized, tuple) and len(quantized) == 2:
        return quantized
    raise MXNetError(
        "quantized= expects a QuantizedParams artifact, an artifact "
        "prefix, or a (symbol_json, param_bytes) pair; got %r"
        % type(quantized).__name__)


class ModelRegistry(object):
    """Owns the live engine for one model and swaps it atomically.

    Parameters mirror :class:`serving.Predictor`: the symbol stays fixed
    across weight swaps (a quantized swap substitutes the artifact's own
    rewritten symbol), the params blob is what rotates.
    """

    def __init__(self, symbol_json, param_bytes, input_shapes,
                 dev_type=1, dev_id=0, input_types=None, config=None):
        self._symbol_json = symbol_json
        self._input_shapes = dict(input_shapes)
        self._dev = (dev_type, dev_id)
        self._input_types = input_types
        self._cfg = config or ServeConfig()
        self._lock = threading.Lock()
        self._decode = None
        self._m_swaps = _tm.counter(
            "serving/swaps_total", "Model hot-swaps completed")
        # shadow A/B state: mirrored requests are sampled from a PRIVATE
        # stream (tracing.py discipline: user random.seed() streams must
        # never diverge because shadow mode is on)
        self._shadow = None
        self._shadow_fraction = 0.0
        self._shadow_rng = _random.Random(0x5AD0)
        self._shadow_pending = deque()
        self._shadow_cond = threading.Condition()
        self._shadow_thread = None
        self._shadow_drifts = deque(maxlen=512)
        self._quantized_active = False
        self._engine = self._build(param_bytes)

    def _build(self, param_bytes, symbol_json=None):
        from ..serving import Predictor
        pred = Predictor(symbol_json or self._symbol_json, param_bytes,
                         dev_type=self._dev[0], dev_id=self._dev[1],
                         input_shapes=self._input_shapes,
                         input_types=self._input_types)
        return InferenceEngine(pred, self._cfg).start()

    # -- engine access -----------------------------------------------------
    def engine(self):
        """The CURRENT engine (atomic read; may be superseded by a
        concurrent swap — use :meth:`submit`/:meth:`predict`, which
        retry across swaps, unless you hold it only briefly)."""
        with self._lock:
            return self._engine

    @property
    def ready(self):
        return self.engine().ready

    @property
    def quantized_active(self):
        """Whether the live engine is serving a quantized variant."""
        return self._quantized_active

    def warmup(self):
        self.engine().warmup()
        return self

    def submit(self, feed, timeout_ms=None, ctx=None):
        """Engine submit that is safe across a concurrent swap: a
        request refused because ITS engine started draining re-routes
        to the replacement instead of surfacing a 503. With shadow mode
        on, a sampled fraction of accepted requests is also mirrored to
        the shadow engine (drift recorded asynchronously; mirror
        failures never surface to the caller)."""
        while True:
            eng = self.engine()
            try:
                req = eng.submit(feed, timeout_ms, ctx=ctx)
                break
            except EngineClosedError:
                if self.engine() is eng:     # closed for real, no swap
                    raise
                # else: swapped between the read and the submit; retry
        shadow = self._shadow
        if shadow is not None \
                and self._shadow_rng.random() < self._shadow_fraction:
            self._mirror(shadow, req, feed, timeout_ms, ctx)
        return req

    def predict(self, feed, timeout_ms=None):
        return self.submit(feed, timeout_ms).result()

    # -- shadow A/B --------------------------------------------------------
    def _mirror(self, shadow, req, feed, timeout_ms, ctx):
        if len(self._shadow_pending) >= _SHADOW_PENDING_MAX:
            # compare thread is behind (shadow slower than primary):
            # shed the sample BEFORE submitting to the shadow engine
            _tm.counter("quantize/shadow_dropped_total",
                        "Shadow mirrors dropped (shadow engine "
                        "saturated, closed, or compare backlog "
                        "full)").inc()
            return
        try:
            sreq = shadow.submit(feed, timeout_ms, ctx=ctx)
        except MXNetError:
            # shadow saturated/closed: the canary drops a sample, the
            # primary request is untouched
            _tm.counter("quantize/shadow_dropped_total",
                        "Shadow mirrors dropped (shadow engine "
                        "saturated, closed, or compare backlog "
                        "full)").inc()
            return
        _tm.counter("quantize/shadow_requests_total",
                    "Requests mirrored to the shadow engine").inc()
        with self._shadow_cond:
            self._shadow_pending.append(
                (req, sreq, ctx if ctx is not None else _tr.active(),
                 _tm.monotonic()))
            self._shadow_cond.notify()

    def enable_shadow(self, quantized, fraction=None):
        """Mirror a fraction of live requests to a shadow engine built
        from ``quantized`` (a QuantizedParams artifact / artifact
        prefix / ``(symbol_json, param_bytes)`` pair) — the int8 canary
        under real traffic.

        The shadow engine is built and WARMED here (its bucket compiles
        land before any mirror runs, so shadow mode adds zero compiles
        to live traffic). Each mirrored request's outputs are compared
        against the primary's on a side thread: the max absolute
        element difference lands in ``quantize/shadow_drift`` (exposed
        on ``/metrics``) and as a ``serve.shadow`` span in the
        request's trace. ``fraction`` defaults to
        ``MXNET_SERVE_SHADOW_FRACTION``. Returns the shadow engine.
        """
        from ..config import get as _cfg
        if fraction is None:
            fraction = float(_cfg("MXNET_SERVE_SHADOW_FRACTION"))
        if not 0.0 <= fraction <= 1.0:
            raise MXNetError("shadow fraction must be in [0, 1], got %r"
                             % (fraction,))
        if self._shadow is not None:
            raise MXNetError("shadow mode already enabled; "
                             "disable_shadow() first")
        symbol_json, param_bytes = _resolve_quantized(quantized)
        eng = self._build(param_bytes, symbol_json=symbol_json)
        eng.warmup()
        # register the drift instruments HERE (first registration wins
        # the bucket layout) so they carry magnitude buckets however a
        # scraper races the first mirror
        _tm.histogram(
            "quantize/shadow_drift",
            "Max abs output difference, shadowed quantized engine vs "
            "primary, per mirrored request", buckets=_DRIFT_BUCKETS)
        with self._shadow_cond:
            # a fresh canary must not score pairs left over from a
            # previous one (a mirror that raced disable_shadow would
            # otherwise feed OLD-engine drift into the NEW histogram)
            self._shadow_pending.clear()
            self._shadow_drifts.clear()
            self._shadow_fraction = float(fraction)
            self._shadow = eng
            if self._shadow_thread is None \
                    or not self._shadow_thread.is_alive():
                self._shadow_thread = threading.Thread(
                    target=self._shadow_main, name="mxnet-serve-shadow",
                    daemon=True)
                self._shadow_thread.start()
        return eng

    def disable_shadow(self, drain_timeout=30.0):
        """Stop mirroring and tear the shadow engine down (pending
        comparisons finish first — their drift still lands)."""
        with self._shadow_cond:
            eng, self._shadow = self._shadow, None
            self._shadow_fraction = 0.0
            self._shadow_cond.notify_all()
        if eng is None:
            return
        thread = self._shadow_thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=drain_timeout)
        self._shadow_thread = None
        eng.close(drain=True, timeout=drain_timeout)

    def shadow_report(self):
        """Operator summary of the canary so far: mirrored count and
        drift percentiles over the recent window (the full history
        lives in the ``quantize/shadow_drift`` histogram)."""
        drifts = sorted(self._shadow_drifts)

        def pct(p):
            if not drifts:
                return None
            return drifts[min(len(drifts) - 1,
                              int(p / 100.0 * len(drifts)))]

        fam = _tm.REGISTRY._families.get("quantize/shadow_drift")
        count = sum(c.count for _lv, c in fam.series()) if fam else 0
        return {"active": self._shadow is not None,
                "fraction": self._shadow_fraction,
                "compared_total": count,
                "window": len(drifts),
                "drift_max": drifts[-1] if drifts else None,
                "drift_p50": pct(50), "drift_p99": pct(99)}

    def _shadow_main(self):
        """Compare worker: waits for (primary, shadow) result pairs and
        records drift. Exits once shadow mode is disabled AND the
        pending queue is drained."""
        hist = _tm.histogram(
            "quantize/shadow_drift",
            "Max abs output difference, shadowed quantized engine vs "
            "primary, per mirrored request", buckets=_DRIFT_BUCKETS)
        errs = _tm.counter(
            "quantize/shadow_errors_total",
            "Shadow comparisons that failed (either side errored)")
        while True:
            with self._shadow_cond:
                while not self._shadow_pending:
                    if self._shadow is None:
                        return
                    self._shadow_cond.wait(0.1)
                req, sreq, ctx, t0 = self._shadow_pending.popleft()
            try:
                a = req.result()
                b = sreq.result()
                drift = 0.0
                for x, y in zip(a, b):
                    d = abs(x.astype("float32") - y.astype("float32"))
                    drift = max(drift, float(d.max()) if d.size else 0.0)
            except MXNetError:
                errs.inc()
                continue
            t1 = _tm.monotonic()
            hist.observe(drift,
                         trace_id=ctx.trace_id if ctx is not None else None)
            self._shadow_drifts.append(drift)
            if ctx is not None and ctx.sampled:
                _tr.record_span("serve.shadow", ctx, t0, t1,
                                attrs={"drift": drift})

    # -- decode attachment -------------------------------------------------
    def attach_decode(self, engine):
        """Attach a :class:`~mxnet_tpu.serve.decode.DecodeEngine`
        serving this model's autoregressive traffic. :meth:`swap` then
        DRAINS its decode sessions before the hot-swap (every in-flight
        generation finishes before the flip; pass ``decode_params`` to
        rotate the decode weights inside the same quiesced window), and
        :func:`serve_http` routes ``POST /generate`` to it."""
        self._decode = engine
        return engine

    def decode_engine(self):
        """The attached decode engine, or None."""
        return self._decode

    # -- lifecycle ---------------------------------------------------------
    def swap(self, param_bytes=None, drain_timeout=30.0,
             decode_params=None, quantized=None):
        """Hot-swap the serving variant with zero dropped requests.

        ``param_bytes`` rotates the weights under the registry's fixed
        symbol (the classic weight swap). ``quantized=`` swaps to a
        calibrated int8 artifact instead (QuantizedParams / artifact
        prefix / ``(symbol_json, param_bytes)``): the artifact's own
        rewritten symbol builds the replacement engine, everything else
        — warm-before-flip, decode drain, old-engine drain — is
        UNCHANGED; a later ``swap(param_bytes)`` rolls back to fp32.

        Builds + warms the replacement engine while the old one keeps
        serving, DRAINS any attached decode engine's sessions BEFORE
        the flip (each in-flight generation finishes on the weights it
        started with; new ``/generate`` admissions 503 for the drain
        window), flips the active reference atomically, then drains the
        old engine (its queued requests complete on the old weights).

        ``decode_params``: the decode engine's new transformer weight
        pytree (its weights are a separate artifact from the predictor
        blob). When given, they rotate inside the quiesced window — the
        predictor flip and the decode weights move together, so no
        generation and no scoring batch ever mixes versions. When
        omitted, the decode engine keeps its current weights (the drain
        still quiesces decode across the flip); call
        ``DecodeEngine.swap_params`` separately if they rotate on their
        own cadence. Returns the new engine."""
        if (param_bytes is None) == (quantized is None):
            raise MXNetError(
                "swap needs exactly one of param_bytes (fp32 weight "
                "rotation) or quantized= (int8 artifact)")
        if quantized is not None:
            symbol_json, param_bytes = _resolve_quantized(quantized)
            new = self._build(param_bytes, symbol_json=symbol_json)
        else:
            new = self._build(param_bytes)
        try:
            new.warmup()                  # compiles land BEFORE the flip
        except Exception:
            # failed rollout must not leak the replacement's workers or
            # its HBM weight copy; the old engine keeps serving
            new.close(drain=False)
            raise
        decode = self._decode
        if decode is not None:
            # decode sessions drain BEFORE the flip: generation state
            # (the KV cache) is weight-coupled in a way stateless
            # predict batches are not
            if not decode.pause(drain=True, timeout=drain_timeout):
                decode.resume()
                new.close(drain=False)
                raise MXNetError(
                    "decode sessions did not drain within %.1fs; "
                    "swap aborted, old weights still serving"
                    % drain_timeout)
            if decode_params is not None:
                # engine is idle (paused + drained): a plain rebind is
                # race-free, and programs take params as traced
                # arguments, so no recompiles either
                decode._params = decode_params
        try:
            with self._lock:
                old, self._engine = self._engine, new
                self._quantized_active = quantized is not None
        finally:
            if decode is not None:
                decode.resume()
        self._m_swaps.inc()
        if quantized is not None:
            _tm.counter("quantize/swaps_total",
                        "Hot-swaps to a quantized int8 variant").inc()
        try:
            from .. import blackbox as _bb
            _bb.record_event("swap", quantized=quantized is not None,
                             decode_rotated=decode_params is not None)
        except Exception:
            pass
        old.close(drain=True, timeout=drain_timeout)
        return new

    def close(self, drain=True, timeout=30.0):
        self.disable_shadow(drain_timeout=timeout)
        if self._decode is not None:
            self._decode.close(drain=drain, timeout=timeout)
        self.engine().close(drain=drain, timeout=timeout)
