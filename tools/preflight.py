#!/usr/bin/env python
"""Pre-snapshot gate: the full test suite AND the multi-chip dryrun.

Run this before EVERY snapshot/commit of consequence:

    python tools/preflight.py            # pytest + dryrun_multichip(8)
    python tools/preflight.py --fast     # dryrun only (seconds)

Both legs run on a virtual 8-device CPU mesh
(``--xla_force_host_platform_device_count=8``), the same configuration
the driver uses for ``MULTICHIP_r*.json`` — so a green preflight means
the driver gate passes too. Exits non-zero on any failure.
"""
import argparse
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ENV = dict(
    os.environ,
    JAX_PLATFORMS="cpu",
    # fatal signals print a Python traceback instead of a bare abort
    PYTHONFAULTHANDLER="1",
    XLA_FLAGS=(os.environ.get("XLA_FLAGS", "") +
               " --xla_force_host_platform_device_count=8").strip(),
)


def run(name, cmd):
    print("== preflight: %s ==" % name, flush=True)
    rc = subprocess.call(cmd, cwd=REPO, env=ENV)
    if rc < 0:
        # crash-class exit (signal), not test failures: observed once as
        # a transient SIGABRT under concurrent load that did not
        # reproduce — retry once so a one-off doesn't fail the gate
        print("== preflight: %s crashed with signal %d; retrying once =="
              % (name, -rc), flush=True)
        rc = subprocess.call(cmd, cwd=REPO, env=ENV)
    print("== preflight: %s -> %s ==" % (name, "OK" if rc == 0 else
                                         "FAIL rc=%d" % rc), flush=True)
    return rc


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--fast", action="store_true",
                    help="skip pytest; dryrun_multichip only")
    ap.add_argument("--pytest-args", default="-q",
                    help="extra args for pytest (default -q)")
    args = ap.parse_args()

    rcs = []
    if not args.fast:
        rcs.append(run("pytest", [sys.executable, "-m", "pytest", "tests/"]
                       + args.pytest_args.split()))
    rcs.append(run("dryrun_multichip(8)",
                   [sys.executable, "__graft_entry__.py"]))
    if any(rcs):
        print("PREFLIGHT FAILED", flush=True)
        return 1
    print("PREFLIGHT OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
