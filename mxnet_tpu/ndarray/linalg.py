"""nd.linalg namespace (reference: python/mxnet/ndarray/linalg.py —
wrappers over the _linalg_* ops from src/operator/tensor/la_op.cc)."""
from __future__ import annotations

from .ndarray import invoke_op

__all__ = ["gemm", "gemm2", "potrf", "potri", "trsm", "trmm", "sumlogdiag",
           "syrk", "gelqf", "syevd", "extractdiag", "makediag",
           "extracttrian", "maketrian", "inverse", "det", "slogdet"]


def _make(name, n_arrays):
    def fn(*args, **attrs):
        arrays = list(args[:n_arrays])
        return invoke_op("_linalg_" + name, arrays, dict(attrs))
    fn.__name__ = name
    fn.__doc__ = ("linalg.%s (reference: src/operator/tensor/la_op.cc "
                  "linalg_%s)" % (name, name))
    return fn


gemm = _make("gemm", 3)
gemm2 = _make("gemm2", 2)
potrf = _make("potrf", 1)
potri = _make("potri", 1)
trsm = _make("trsm", 2)
trmm = _make("trmm", 2)
sumlogdiag = _make("sumlogdiag", 1)
syrk = _make("syrk", 1)
gelqf = _make("gelqf", 1)
syevd = _make("syevd", 1)
extractdiag = _make("extractdiag", 1)
makediag = _make("makediag", 1)
extracttrian = _make("extracttrian", 1)
maketrian = _make("maketrian", 1)
inverse = _make("inverse", 1)
det = _make("det", 1)
slogdet = _make("slogdet", 1)
