"""Symbol API tests (reference: tests/python/unittest/test_symbol.py,
test_infer_shape.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym


def _mlp():
    data = sym.Variable("data")
    fc1 = sym.FullyConnected(data, num_hidden=64, name="fc1")
    act = sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = sym.FullyConnected(act, num_hidden=10, name="fc2")
    return sym.SoftmaxOutput(fc2, name="softmax")


def test_list_arguments():
    out = _mlp()
    assert out.list_arguments() == [
        "data", "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias",
        "softmax_label"]
    assert out.list_outputs() == ["softmax_output"]


def test_infer_shape():
    out = _mlp()
    arg_shapes, out_shapes, aux_shapes = out.infer_shape(data=(32, 784))
    assert arg_shapes == [(32, 784), (64, 784), (64,), (10, 64), (10,), (32,)]
    assert out_shapes == [(32, 10)]
    assert aux_shapes == []


def test_infer_shape_conv():
    data = sym.Variable("data")
    conv = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                           name="conv1")
    bn = sym.BatchNorm(conv, name="bn1")
    arg_shapes, out_shapes, aux_shapes = bn.infer_shape(data=(2, 3, 8, 8))
    assert arg_shapes[1] == (8, 3, 3, 3)       # conv weight
    assert out_shapes == [(2, 8, 8, 8)]
    assert aux_shapes == [(8,), (8,)]          # moving mean/var
    assert bn.list_auxiliary_states() == ["bn1_moving_mean", "bn1_moving_var"]


def test_infer_type():
    out = _mlp()
    arg_types, out_types, _ = out.infer_type(data=np.float32)
    assert out_types[0] == np.float32


def test_json_roundtrip():
    out = _mlp()
    js = out.tojson()
    out2 = sym.load_json(js)
    assert out2.list_arguments() == out.list_arguments()
    assert out2.list_outputs() == out.list_outputs()
    a1, o1, _ = out.infer_shape(data=(8, 32))
    a2, o2, _ = out2.infer_shape(data=(8, 32))
    assert o1 == o2


def test_save_load(tmp_path):
    out = _mlp()
    f = str(tmp_path / "net.json")
    out.save(f)
    out2 = sym.load(f)
    assert out2.list_arguments() == out.list_arguments()


def test_compose():
    data = sym.Variable("data")
    net1 = sym.FullyConnected(data, name="fc1", num_hidden=10)
    net2 = sym.FullyConnected(name="fc3", num_hidden=10)
    composed = net2(data=net1, name="composed")
    args = composed.list_arguments()
    assert "fc1_weight" in args and "fc3_weight" in args


def test_group_and_getitem():
    data = sym.Variable("data")
    fc1 = sym.FullyConnected(data, num_hidden=4, name="fc1")
    fc2 = sym.FullyConnected(data, num_hidden=6, name="fc2")
    g = sym.Group([fc1, fc2])
    assert g.list_outputs() == ["fc1_output", "fc2_output"]
    assert g[1].list_outputs() == ["fc2_output"]
    assert g["fc1_output"].list_outputs() == ["fc1_output"]


def test_get_internals():
    out = _mlp()
    internals = out.get_internals()
    assert "fc1_output" in internals.list_outputs()
    fc1 = internals["fc1_output"]
    _, out_shapes, _ = fc1.infer_shape(data=(4, 16))
    assert out_shapes == [(4, 64)]


def test_symbol_arithmetic_exec():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = 2.0 * a + b
    ex = c.bind(mx.cpu(), {"a": mx.nd.array([[1.0, 2.0]]),
                           "b": mx.nd.array([[3.0, 4.0]])})
    out = ex.forward()[0]
    np.testing.assert_allclose(out.asnumpy(), [[5.0, 8.0]])


def test_executor_forward_backward():
    data = sym.Variable("data")
    fc = sym.FullyConnected(data, num_hidden=3, name="fc")
    loss = sym.LinearRegressionOutput(fc, name="lro")
    ex = loss.simple_bind(mx.cpu(), data=(4, 5))
    rng = np.random.RandomState(0)
    ex.arg_dict["fc_weight"][:] = rng.randn(3, 5).astype(np.float32)
    x = rng.randn(4, 5).astype(np.float32)
    y = rng.randn(4, 3).astype(np.float32)
    ex.forward(is_train=True, data=x, lro_label=y)
    ex.backward()
    # numeric check of the loss-op gradient: d/dpred 0.5*(pred-y)^2 = pred-y
    pred = x @ ex.arg_dict["fc_weight"].asnumpy().T
    gw = ex.grad_dict["fc_weight"].asnumpy()
    expected_gw = (pred - y).T @ x / 1.0
    np.testing.assert_allclose(gw, expected_gw, rtol=1e-4, atol=1e-4)


def test_batchnorm_aux_update_in_executor():
    data = sym.Variable("data")
    bn = sym.BatchNorm(data, name="bn", momentum=0.5, fix_gamma=False)
    ex = bn.simple_bind(mx.cpu(), data=(8, 3))
    ex.arg_dict["bn_gamma"][:] = 1.0
    ex.aux_dict["bn_moving_var"][:] = 1.0
    x = np.random.RandomState(1).randn(8, 3).astype(np.float32) * 2 + 5
    ex.forward(is_train=True, data=x)
    mm = ex.aux_dict["bn_moving_mean"].asnumpy()
    # moving_mean = 0.5*0 + 0.5*batch_mean
    np.testing.assert_allclose(mm, 0.5 * x.mean(axis=0), rtol=1e-4)
    # inference uses moving stats
    out = ex.forward(is_train=False, data=x)[0].asnumpy()
    expect = (x - mm) / np.sqrt(ex.aux_dict["bn_moving_var"].asnumpy() + 1e-3)
    np.testing.assert_allclose(out, expect, rtol=1e-3, atol=1e-3)


def test_variable_shape_attr():
    data = sym.Variable("data", shape=(4, 7))
    fc = sym.FullyConnected(data, num_hidden=2, name="fc")
    arg_shapes, out_shapes, _ = fc.infer_shape()
    assert arg_shapes[0] == (4, 7)
    assert out_shapes == [(4, 2)]


def test_inception_bn_symbol_builds_and_runs():
    """Inception-BN topology (reference:
    example/image-classification/symbols/inception-bn.py; the missing
    column of the benchmark_score tables). Checks the module concat
    widths and a finite forward."""
    from mxnet_tpu.models import inception_bn
    sym = inception_bn(num_classes=1000)
    args, outs, auxs = sym.infer_shape(data=(2, 3, 224, 224),
                                       softmax_label=(2,))
    assert outs == [(2, 1000)]
    assert len(auxs) == 138        # 69 BN layers x (mean, var)
    exe = sym.simple_bind(data=(1, 3, 224, 224))
    rng = np.random.RandomState(0)
    for n, a in exe.arg_dict.items():
        if n != "data":
            a[:] = mx.nd.array(rng.randn(*a.shape).astype(np.float32) * .05)
    exe.arg_dict["data"][:] = mx.nd.array(
        rng.randn(1, 3, 224, 224).astype(np.float32))
    out = exe.forward(is_train=False)[0].asnumpy()
    assert np.isfinite(out).all()
    np.testing.assert_allclose(out.sum(), 1.0, rtol=1e-4)


def test_symbol_sub_namespaces():
    """sym.contrib / sym.linalg / sym.random mirror the nd namespaces
    (reference: python/mxnet/symbol/{contrib,linalg,random}.py)."""
    import mxnet_tpu.symbol as S
    # contrib exposes every _contrib_ op under its public name
    for n in ("ROIAlign", "box_nms", "MultiBoxPrior", "CTCLoss",
              "flash_attention", "BilinearResize2D"):
        assert callable(getattr(S.contrib, n)), n
    for n in ("gemm2", "potrf", "trsm", "syrk", "inverse", "slogdet"):
        assert callable(getattr(S.linalg, n)), n

    # linalg numeric check through the executor
    A = mx.sym.var("A")
    out = S.linalg.potrf(A)
    exe = out.simple_bind(A=(1, 3, 3))
    m = np.array([[[4., 2, 0], [2, 5, 1], [0, 1, 6]]], np.float32)
    exe.arg_dict["A"][:] = mx.nd.array(m)
    L = exe.forward()[0].asnumpy()
    np.testing.assert_allclose(L @ np.swapaxes(L, 1, 2), m, rtol=1e-4,
                               atol=1e-4)

    # random symbols draw fresh values per executor step
    r = S.random.normal(0, 1, shape=(64,))
    exe2 = r.simple_bind()
    a = exe2.forward(is_train=True)[0].asnumpy().copy()
    b = exe2.forward(is_train=True)[0].asnumpy().copy()
    assert not np.allclose(a, b)


def test_symbolic_control_flow():
    """sym.contrib.foreach / while_loop / cond build subgraph nodes and
    lower to lax.scan / masked-scan / lax.cond at eval (reference:
    symbol/contrib.py:215+, src/operator/control_flow.cc)."""
    import mxnet_tpu.symbol as S
    rng = np.random.RandomState(0)

    # foreach: cumulative x_t @ w, outputs stacked on axis 0
    data = mx.sym.var("data")
    w = mx.sym.var("w")

    def body(x_t, state):
        h = mx.sym.dot(x_t, w) + state
        return h, h

    outs, final = S.contrib.foreach(body, data, mx.sym.var("s0"))
    exe = outs.simple_bind(data=(4, 2, 3), w=(3, 3), s0=(2, 3))
    d = rng.randn(4, 2, 3).astype(np.float32)
    wv = rng.randn(3, 3).astype(np.float32)
    exe.arg_dict["data"][:] = mx.nd.array(d)
    exe.arg_dict["w"][:] = mx.nd.array(wv)
    exe.arg_dict["s0"][:] = mx.nd.zeros((2, 3))
    got = exe.forward(is_train=True)[0].asnumpy()
    ref, st = [], np.zeros((2, 3), np.float32)
    for t in range(4):
        st = d[t] @ wv + st
        ref.append(st)
    np.testing.assert_allclose(got, np.stack(ref), rtol=1e-5, atol=1e-5)

    # differentiable: grad of sum(outputs) w.r.t. w matches numeric
    exe.backward(mx.nd.ones((4, 2, 3)))
    gw = exe.grad_dict["w"].asnumpy()
    # d(sum_t sum(cumsum_t(d@w))) / dw = sum_t (T - t) * d[t]^T @ 1
    ref_g = np.zeros((3, 3), np.float32)
    for t in range(4):
        ref_g += (4 - t) * d[t].T @ np.ones((2, 3), np.float32)
    np.testing.assert_allclose(gw, ref_g, rtol=1e-4, atol=1e-4)

    # cond: branch picked by a traced predicate
    x = mx.sym.var("x")
    out = S.contrib.cond(lambda: mx.sym.sum(x) > 0,
                         lambda: x * 2.0, lambda: x - 1.0)
    exe2 = out.simple_bind(x=(3,))
    exe2.arg_dict["x"][:] = mx.nd.array(np.array([1., 2, 3], np.float32))
    np.testing.assert_allclose(exe2.forward()[0].asnumpy(), [2, 4, 6])
    exe2.arg_dict["x"][:] = mx.nd.array(np.array([-1, -2, -3], np.float32))
    np.testing.assert_allclose(exe2.forward()[0].asnumpy(), [-2, -3, -4])

    # while_loop: doubling until the sum reaches 100 (bounded, masked)
    s = mx.sym.var("s")
    _outs, fin = S.contrib.while_loop(
        lambda st: mx.sym.sum(st) < 100.0,
        lambda st: (st, st * 2.0), s, max_iterations=10)
    exe3 = fin.simple_bind(s=(2,))
    exe3.arg_dict["s"][:] = mx.nd.array(np.array([1., 1.], np.float32))
    np.testing.assert_allclose(exe3.forward()[0].asnumpy(), [64, 64])


def test_symbol_comparison_operators():
    x = mx.sym.var("x")
    y = mx.sym.var("y")
    for op, ref in ((x > y, np.greater), (x >= y, np.greater_equal),
                    (x < y, np.less), (x <= y, np.less_equal),
                    (x > 1.5, None)):
        a = np.array([1., 2, 2, 3], np.float32)
        b = np.array([2., 2, 1, 1], np.float32)
        if ref is not None:
            exe = op.simple_bind(x=(4,), y=(4,))
            exe.arg_dict["y"][:] = mx.nd.array(b)
        else:
            exe = op.simple_bind(x=(4,))
        exe.arg_dict["x"][:] = mx.nd.array(a)
        got = exe.forward()[0].asnumpy()
        if ref is not None:
            np.testing.assert_array_equal(got, ref(a, b).astype(np.float32))
        else:
            np.testing.assert_array_equal(got, (a > 1.5).astype(np.float32))


def test_symbolic_control_flow_nesting_and_shared_vars():
    """Regressions: (a) nested foreach must capture the OUTER trace's
    state (placeholder names are unique per trace); (b) a free variable
    used both inside and outside the loop must appear once in
    list_arguments and survive backward."""
    import mxnet_tpu.symbol as S
    data = mx.sym.var("d")
    inner_data = mx.sym.var("d2")

    def outer_body(x, s):
        def inner_body(x2, s2):
            return x2 + s, s2          # closes over OUTER state
        inner_outs, _ = S.contrib.foreach(inner_body, inner_data,
                                          mx.sym.var("z0"))
        total = mx.sym.sum(inner_outs, axis=0) + x + s
        return total, total

    outs, _fin = S.contrib.foreach(outer_body, data, mx.sym.var("s0"))
    exe = outs.simple_bind(d=(2, 2), d2=(3, 2), z0=(2,), s0=(2,))
    exe.arg_dict["d"][:] = mx.nd.zeros((2, 2))
    exe.arg_dict["d2"][:] = mx.nd.zeros((3, 2))
    exe.arg_dict["z0"][:] = mx.nd.zeros((2,))
    exe.arg_dict["s0"][:] = mx.nd.array(np.array([10., 10], np.float32))
    np.testing.assert_allclose(exe.forward()[0].asnumpy(),
                               [[40, 40], [160, 160]])

    w = mx.sym.var("w")
    d3 = mx.sym.var("d3")
    outs2, _ = S.contrib.foreach(
        lambda x, s: ((mx.sym.dot(x, w) + s,) * 2), d3, mx.sym.var("s1"))
    t = mx.sym.sum(outs2) + mx.sym.sum(w)
    assert t.list_arguments().count("w") == 1
    exe2 = t.simple_bind(d3=(4, 2, 3), w=(3, 3), s1=(2, 3))
    rng = np.random.RandomState(0)
    exe2.arg_dict["d3"][:] = mx.nd.array(
        rng.randn(4, 2, 3).astype(np.float32))
    exe2.arg_dict["w"][:] = mx.nd.array(rng.randn(3, 3).astype(np.float32))
    exe2.arg_dict["s1"][:] = mx.nd.zeros((2, 3))
    exe2.forward(is_train=True)
    exe2.backward(mx.nd.ones(()))
    assert np.isfinite(exe2.grad_dict["w"].asnumpy()).all()


def test_control_flow_capture_aux_and_inner_shapes():
    """Regressions from review: (a) a stochastic node closed over by a
    loop body is computed ONCE in the outer graph and shared (not
    re-drawn per iteration); (b) BatchNorm moving stats update through
    control-flow bodies; (c) auto-created params inside a body
    shape-deduce through the subgraph; (d) Symbol has no truth value."""
    import mxnet_tpu.symbol as S
    rng = np.random.RandomState(0)

    x = mx.sym.var("x")
    h = mx.sym.Dropout(x, p=0.5, name="drop")
    outs, _ = S.contrib.foreach(lambda t, s: (h + 0 * t, s),
                                mx.sym.var("dd"), mx.sym.var("ss"))
    total = mx.sym.Group([outs, h])
    exe = total.simple_bind(x=(64,), dd=(2, 64), ss=(1,))
    exe.arg_dict["x"][:] = mx.nd.array(np.ones(64, np.float32))
    exe.arg_dict["dd"][:] = mx.nd.zeros((2, 64))
    exe.arg_dict["ss"][:] = mx.nd.zeros((1,))
    o = exe.forward(is_train=True)
    np.testing.assert_array_equal(o[0].asnumpy()[0], o[1].asnumpy())
    np.testing.assert_array_equal(o[0].asnumpy()[1], o[1].asnumpy())

    data = mx.sym.var("data")
    outs2, _ = S.contrib.foreach(
        lambda xt, s: (mx.sym.BatchNorm(xt, name="bn", fix_gamma=False),
                       s), data, mx.sym.var("s2"))
    exe2 = outs2.simple_bind(data=(3, 4, 5), s2=(1,))
    for n, a in exe2.arg_dict.items():
        if n not in ("data", "s2"):
            a[:] = mx.nd.ones(a.shape)
    exe2.arg_dict["data"][:] = mx.nd.array(
        (rng.randn(3, 4, 5) * 3 + 7).astype(np.float32))
    exe2.arg_dict["s2"][:] = mx.nd.zeros((1,))
    before = {k: v.asnumpy().copy() for k, v in exe2.aux_dict.items()}
    exe2.forward(is_train=True)
    assert any(not np.allclose(before[k], exe2.aux_dict[k].asnumpy())
               for k in before)

    outs3, _ = S.contrib.foreach(
        lambda xt, s: (mx.sym.FullyConnected(xt, num_hidden=4,
                                             name="fc") + 0 * s, s),
        mx.sym.var("dd2"), mx.sym.var("ss2"))
    exe3 = outs3.simple_bind(dd2=(5, 2, 3), ss2=(2, 4))
    assert exe3.arg_dict["fc_weight"].shape == (4, 3)

    with pytest.raises(TypeError):
        bool(mx.sym.var("q") > 0)


def test_thread_local_scopes_isolated():
    """Per-thread isolation of naming/attr/context/autograd scopes
    (reference: tests/python/unittest/test_thread_local.py)."""
    import threading

    results = {}

    def worker():
        with mx.name.Prefix("w_"):
            with mx.AttrScope(ctx_group="dev9"):
                s = mx.sym.FullyConnected(mx.sym.var("wd"), num_hidden=2)
                results["name"] = s.name
                results["attr"] = s.attr("ctx_group")
        with mx.Context("cpu", 1):
            results["ctx"] = mx.context.current_context().device_id
        results["recording"] = mx.autograd.is_recording()

    with mx.name.Prefix("main_"):
        with mx.AttrScope(ctx_group="dev0"):
            with mx.autograd.record():
                t = threading.Thread(target=worker)
                t.start()
                t.join(timeout=30)
            s_main = mx.sym.FullyConnected(mx.sym.var("d"), num_hidden=2)
    assert results["name"].startswith("w_"), results
    assert results["attr"] == "dev9"
    assert results["ctx"] == 1
    assert results["recording"] is False      # record() is per-thread
    assert s_main.name.startswith("main_")
    assert s_main.attr("ctx_group") == "dev0"
    assert mx.context.current_context().device_id == 0
