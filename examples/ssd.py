"""SSD-style single-shot detector smoke training.

Capability analog of the reference's SSD example (reference:
example/ssd/train.py + symbol/symbol_builder.py): a small conv backbone
produces two feature maps; per-map MultiBoxPrior anchors with cls/loc
convolution heads; MultiBoxTarget assigns training targets with hard
negative mining; loss = softmax CE over classes (ignoring -1 anchors) +
smooth-L1 on the masked location offsets; MultiBoxDetection decodes at
inference. Everything jits through the standard autograd path — the
matching/NMS ops are the vectorized TPU formulations in
ops/detection_ops.py.

Run: python examples/ssd.py
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx                                     # noqa: E402
from mxnet_tpu import autograd, nd                         # noqa: E402
from mxnet_tpu import optimizer as opt                     # noqa: E402


def _conv(x, w, b, stride=1):
    y = nd.Convolution(x, w, b, kernel=w.shape[2:], stride=(stride, stride),
                       pad=(1, 1), num_filter=w.shape[0])
    return nd.relu(y)


class TinySSD(object):
    """Two-scale SSD head over a 3-layer backbone."""

    def __init__(self, num_classes=3, seed=0):
        rng = np.random.RandomState(seed)
        self.num_classes = num_classes          # foreground classes
        self.sizes = [(0.2, 0.35), (0.5, 0.75)]
        self.ratios = (1.0, 2.0, 0.5)
        self.anchors_per_pos = len(self.sizes[0]) + len(self.ratios) - 1

        def W(*shape):
            a = nd.array((rng.randn(*shape) * 0.05).astype(np.float32))
            a.attach_grad()
            return a

        c = 16
        self.params = {
            "c1": W(c, 3, 3, 3), "b1": W(c),
            "c2": W(c, c, 3, 3), "b2": W(c),
            "c3": W(c, c, 3, 3), "b3": W(c),
            # heads: cls (A*(C+1)) and loc (A*4) per feature map
            "cls1": W(self.anchors_per_pos * (num_classes + 1), c, 3, 3),
            "clb1": W(self.anchors_per_pos * (num_classes + 1)),
            "loc1": W(self.anchors_per_pos * 4, c, 3, 3),
            "lob1": W(self.anchors_per_pos * 4),
            "cls2": W(self.anchors_per_pos * (num_classes + 1), c, 3, 3),
            "clb2": W(self.anchors_per_pos * (num_classes + 1)),
            "loc2": W(self.anchors_per_pos * 4, c, 3, 3),
            "lob2": W(self.anchors_per_pos * 4),
        }

    def all_params(self):
        return list(self.params.values())

    def forward(self, x):
        p = self.params
        f1 = _conv(_conv(x, p["c1"], p["b1"], 2), p["c2"], p["b2"], 2)
        f2 = _conv(f1, p["c3"], p["b3"], 2)
        anchors, cls_preds, loc_preds = [], [], []
        for feat, si, ci, li, cb, lb in ((f1, 0, "cls1", "loc1", "clb1",
                                          "lob1"),
                                         (f2, 1, "cls2", "loc2", "clb2",
                                          "lob2")):
            anchors.append(nd.contrib.MultiBoxPrior(
                feat, sizes=self.sizes[si], ratios=self.ratios))
            cp = nd.Convolution(feat, p[ci], p[cb], kernel=(3, 3),
                                pad=(1, 1), num_filter=p[ci].shape[0])
            lp = nd.Convolution(feat, p[li], p[lb], kernel=(3, 3),
                                pad=(1, 1), num_filter=p[li].shape[0])
            B = cp.shape[0]
            n_pos = cp.shape[2] * cp.shape[3]
            cls_preds.append(cp.transpose((0, 2, 3, 1)).reshape(
                (B, n_pos * self.anchors_per_pos, self.num_classes + 1)))
            loc_preds.append(lp.transpose((0, 2, 3, 1)).reshape(
                (B, n_pos * self.anchors_per_pos * 4)))
        anchors = nd.concat(*anchors, dim=1)
        cls_preds = nd.concat(*cls_preds, dim=1)   # (B, N, C+1)
        loc_preds = nd.concat(*loc_preds, dim=1)   # (B, N*4)
        return anchors, cls_preds, loc_preds


def ssd_loss(cls_preds, cls_target, loc_preds, loc_target, loc_mask):
    """CE over anchors with target >= 0 (ignore -1) + smooth L1 on the
    masked offsets (reference: example/ssd MultiBoxTarget training)."""
    valid = cls_target >= 0
    tgt = nd.broadcast_maximum(cls_target, 0 * cls_target)
    logp = nd.log_softmax(cls_preds, axis=-1)
    ce = -nd.pick(logp, tgt, axis=-1) * valid
    cls_loss = nd.sum(ce) / nd.broadcast_maximum(nd.sum(valid), 1 + 0 * valid[0, 0])
    diff = nd.abs((loc_preds - loc_target) * loc_mask)
    sl1 = nd.where(diff < 1.0, 0.5 * diff * diff, diff - 0.5)
    loc_loss = nd.sum(sl1) / nd.broadcast_maximum(nd.sum(loc_mask),
                                        1 + 0 * loc_mask[0, 0])
    return cls_loss + loc_loss


def synthetic_batch(batch, size, num_classes, rng):
    """Images with one bright axis-aligned rectangle per class id."""
    imgs = rng.rand(batch, 3, size, size).astype(np.float32) * 0.1
    labels = np.full((batch, 2, 5), -1.0, np.float32)
    for b in range(batch):
        for o in range(rng.randint(1, 3)):
            cls = rng.randint(0, num_classes)
            x1, y1 = rng.uniform(0.05, 0.5, 2)
            w, h = rng.uniform(0.2, 0.45, 2)
            x2, y2 = min(x1 + w, 0.95), min(y1 + h, 0.95)
            labels[b, o] = [cls, x1, y1, x2, y2]
            xi = slice(int(x1 * size), int(x2 * size))
            yi = slice(int(y1 * size), int(y2 * size))
            imgs[b, cls % 3, yi, xi] = 1.0
    return imgs, labels


def train(epochs=3, steps_per_epoch=8, batch=8, size=64, num_classes=3,
          lr=0.1, log=print):
    net = TinySSD(num_classes=num_classes)
    optim = opt.create("sgd", learning_rate=lr, momentum=0.9)
    params = net.all_params()
    states = {i: optim.create_state(i, p) for i, p in enumerate(params)}
    rng = np.random.RandomState(0)
    losses = []
    for epoch in range(epochs):
        tot = 0.0
        for _ in range(steps_per_epoch):
            imgs, labels = synthetic_batch(batch, size, num_classes, rng)
            x = nd.array(imgs)
            y = nd.array(labels)
            with autograd.record():
                anchors, cls_preds, loc_preds = net.forward(x)
                with autograd.pause():
                    loc_t, loc_m, cls_t = nd.contrib.MultiBoxTarget(
                        anchors, y, cls_preds.transpose((0, 2, 1)),
                        negative_mining_ratio=3.0)
                loss = ssd_loss(cls_preds, cls_t, loc_preds, loc_t, loc_m)
            loss.backward()
            for i, p in enumerate(params):
                optim.update(i, p, p.grad, states[i])
            tot += float(loss.asscalar())
        losses.append(tot / steps_per_epoch)
        log("epoch %d: loss %.4f" % (epoch, losses[-1]))
    return losses, net


def detect(net, imgs):
    """Decode detections for a batch of images."""
    x = nd.array(imgs)
    anchors, cls_preds, loc_preds = net.forward(x)
    probs = nd.softmax(cls_preds, axis=-1).transpose((0, 2, 1))
    return nd.contrib.MultiBoxDetection(probs, loc_preds, anchors,
                                        nms_threshold=0.45, threshold=0.1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epoch", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--lr", type=float, default=0.1)
    args = ap.parse_args()
    losses, net = train(epochs=args.num_epoch, batch=args.batch_size,
                        lr=args.lr)
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses
    rng = np.random.RandomState(7)
    imgs, _ = synthetic_batch(4, 64, 3, rng)
    out = detect(net, imgs)
    print("detections:", out.shape)


if __name__ == "__main__":
    main()
